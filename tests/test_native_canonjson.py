"""Differential tests: native canonical-JSON encoder vs the json module.

canonical_json is the wire format AND the digest/signing preimage of
every consensus message — a single byte of divergence between the native
encoder (native/canonjson.cpp) and json.dumps(sort_keys=True,
separators=(",", ":")) would fork the committee. These tests enforce
byte-exact equivalence over adversarial content (control characters,
astral planes, lone surrogates, huge ints, deep nesting, non-ASCII and
empty keys) plus real message traffic, and pin the fallback contract for
out-of-subset input.
"""

import json
import random

import pytest

from simple_pbft_tpu import native
from simple_pbft_tpu.messages import (
    Commit,
    NewView,
    PrePrepare,
    Reply,
    Request,
    ViewChange,
    canonical_json,
)

pytestmark = pytest.mark.skipif(
    not native.canonjson_available(), reason="native canonjson unavailable"
)


def _dumps(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


NASTY_STRINGS = [
    "",
    "plain ascii",
    '"quotes" and \\backslashes\\',
    "\x00\x01\x1f\x7f",
    "\b\f\n\r\t",
    "é ü ß π ₿ €",
    "߿ࠀ￿",
    "astral \U0001f600 \U0010fffd",
    "\ud800 lone high",  # lone surrogates survive Python strs
    "lone low \udfff",
    "मिश्रित scripts 混合 نصوص",
]


def test_differential_handcrafted():
    cases = [
        None, True, False, 0, -1, 1, 2**31, -(2**63), 2**63 - 1,
        2**200, -(2**200),
        [], {}, [[]], [{}, []],
        {"": ""}, {"a": None}, {"0": 0, "00": 0, "a b": 1},
        {k: i for i, k in enumerate(NASTY_STRINGS[1:])},
        *NASTY_STRINGS,
        {"nested": [{"deep": [{"er": [1, None, True, "x"]}]}]},
    ]
    for obj in cases:
        assert native.canonjson_encode(obj) == _dumps(obj), repr(obj)[:80]


def test_differential_fuzz():
    rng = random.Random(0xC0FFEE)

    def gen(depth):
        r = rng.random()
        if depth >= 5 or r < 0.35:
            return rng.choice(
                [
                    rng.choice(NASTY_STRINGS),
                    rng.randint(-(2**70), 2**70),
                    rng.randint(-100, 100),
                    None,
                    True,
                    False,
                ]
            )
        if r < 0.65:
            return [gen(depth + 1) for _ in range(rng.randint(0, 4))]
        return {
            rng.choice(NASTY_STRINGS) + str(rng.randint(0, 9)): gen(depth + 1)
            for _ in range(rng.randint(0, 4))
        }

    for _ in range(500):
        obj = gen(0)
        assert native.canonjson_encode(obj) == _dumps(obj), repr(obj)[:120]


def test_real_message_traffic_byte_exact():
    msgs = [
        Request(client_id="c0", timestamp=1785448550156039,
                operation="put kéy   value \U0001f600"),
        PrePrepare(view=3, seq=99, digest="ab" * 32,
                   block=[{"kind": "request", "client_id": "c1",
                           "timestamp": 5, "operation": "x", "sender": "c1",
                           "sig": "cd" * 64}]),
        Commit(view=0, seq=1, digest="00" * 32, bls_share="ff" * 48),
        Reply(view=2, seq=7, client_id="c9", timestamp=42, result="ok",
              superseded=1, mac="aa" * 16),
        ViewChange(new_view=4, stable_seq=64,
                   checkpoint_proof=[{"kind": "checkpoint", "seq": 64,
                                      "state_digest": "ee" * 32}],
                   prepared_proofs=[]),
        NewView(new_view=4, viewchange_proof=[], pre_prepares=[]),
    ]
    for m in msgs:
        d = m.to_dict()
        assert native.canonjson_encode(d) == _dumps(d)
        # the integrated path returns the same bytes (whichever encoder ran)
        assert canonical_json(d) == _dumps(d)


def test_int_subclass_matches_json_repr_semantics():
    """json.dumps formats ints via int.__repr__ regardless of subclass
    overrides; the native encoder must do the same or an int subclass
    with a hostile __str__ would produce divergent digests (and invalid
    JSON) only on natively-equipped replicas."""

    class EvilInt(int):
        def __str__(self):
            return "EVIL"

        __repr__ = __str__

    for v in (EvilInt(7), EvilInt(2**80), EvilInt(-(2**90))):
        obj = {"a": v}
        assert native.canonjson_encode(obj) == _dumps(obj)


def test_out_of_subset_falls_back():
    # floats and non-str keys are not wire types: native returns None and
    # the integrated canonical_json still answers via the json module
    assert native.canonjson_encode({"f": 1.5}) is None
    assert native.canonjson_encode({1: "x"}) is None
    assert canonical_json({"f": 1.5}) == _dumps({"f": 1.5})


def test_encoder_bound_on_depth():
    deep = obj = []
    for _ in range(200):
        inner = []
        obj.append(inner)
        obj = inner
    assert native.canonjson_encode(deep) is None  # RecursionError -> None
    assert canonical_json(deep) == _dumps(deep)  # fallback still answers
