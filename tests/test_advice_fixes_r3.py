"""Directed tests for the round-3 advisor findings (ADVICE.md):

1. Hedged sends on a single-replica committee must not divide by zero
   (the rotation modulus was len(ids) - 1).
2. The nesting-depth guard runs on every frame: the old small-frame
   skip made validity size- and version-dependent (a deep <=1500-byte
   subtree accepted standalone, rejected when embedded in a NewView,
   and a RecursionError risk on CPython <= 3.11 re-encodes).
3. NativeEdVerifier's pubkey row cache and MacBank's shared-key cache
   must stay bounded under adversarial key/peer churn.
4. A mixed superseded/real reply split for one timestamp (a checkpoint
   fold racing a retransmission) triggers one early rebroadcast instead
   of waiting out the full request_timeout.
"""

import asyncio
import json

import pytest

from simple_pbft_tpu.client import Client, SupersededError
from simple_pbft_tpu.config import make_test_committee
from simple_pbft_tpu.crypto import ed25519_cpu
from simple_pbft_tpu.crypto.mac import MacBank
from simple_pbft_tpu.crypto.verifier import BatchItem
from simple_pbft_tpu.messages import Message, Reply


class FakeTransport:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self.q: asyncio.Queue = asyncio.Queue()
        self.sent = []
        self.broadcasts = []

    async def send(self, dest, raw):
        self.sent.append((dest, raw))

    async def broadcast(self, raw, dests):
        self.broadcasts.append((raw, tuple(dests)))

    async def recv(self):
        return await self.q.get()


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_hedged_submit_single_replica_committee():
    """hedge > 0 with n=1: the send path must reach the timeout, not
    die in the hedge rotation's modulus."""

    async def scenario():
        cfg, keys = make_test_committee(n=1, clients=1)
        t = FakeTransport("c0")
        client = Client(
            client_id="c0", cfg=cfg, seed=keys["c0"].seed, transport=t,
            request_timeout=0.05, hedge=2,
        )
        with pytest.raises((asyncio.TimeoutError, TimeoutError)):
            await client.submit("op", retries=0)
        # the one replica got the request; no crash before the send
        assert t.sent and t.sent[0][0] == "r0"

    run(scenario())


def test_deep_small_frame_rejected_on_every_version():
    """A <=1500-byte ViewChange smuggling a >MAX_NESTING-deep subtree
    (wrapped in a dict element so typed-field validation alone doesn't
    catch it) must be rejected by the depth walk on EVERY CPython
    version. A small-frame skip here once made validity size- and
    version-dependent: the same bytes accepted standalone would be
    rejected by backups when embedded in a larger NewView — a
    re-poisonable view-change stall."""
    depth = 600
    deep = json.loads("[" * depth + "]" * depth)
    d = {
        "kind": "viewchange",
        "sender": "r1",
        "new_view": 1,
        "stable_seq": 0,
        "checkpoint_proof": [],
        "prepared_proofs": [{"deep": deep}],
    }
    raw = json.dumps(d, separators=(",", ":")).encode()
    assert len(raw) <= 1500
    with pytest.raises(ValueError, match="nesting"):
        Message.from_wire(raw)
    # sanity: a shallow frame of the same shape parses fine
    d["prepared_proofs"] = [{"deep": []}]
    msg = Message.from_wire(json.dumps(d, separators=(",", ":")).encode())
    assert isinstance(msg.signing_payload(), bytes)


def test_native_verifier_row_cache_bounded():
    try:
        from simple_pbft_tpu.crypto.verifier import NativeEdVerifier

        v = NativeEdVerifier()
    except ImportError:
        pytest.skip("native ed25519 library unavailable")
    v.MAX_KEYS = 4  # shadow the class bound for the test
    v._row_cache.clear()  # process-wide cache: isolate from other tests
    items = []
    for i in range(10):
        seed = bytes([i + 1]) * 32
        pk = ed25519_cpu.public_key(seed)
        msg = b"churn %d" % i
        items.append(BatchItem(pk, msg, ed25519_cpu.sign(seed, msg)))
    out = v.verify_batch(items)
    # correctness is unaffected by the bound: every signature verifies,
    # including the ones whose keys no longer fit in the cache
    assert out == [True] * 10
    assert len(v._row_cache) <= 4
    # uncached keys still verify on a second pass (recomputed per batch)
    assert v.verify_batch(items[-2:]) == [True, True]
    # corrupted sig under an uncached key still rejects
    bad = BatchItem(items[-1].pubkey, items[-1].msg,
                    items[-1].sig[:-1] + bytes([items[-1].sig[-1] ^ 1]))
    assert v.verify_batch([bad]) == [False]


def test_macbank_unknown_peer_not_cached():
    cfg, keys = make_test_committee(n=4, clients=1)
    bank = MacBank(keys["c0"].seed, cfg.kx_pubkeys)
    for i in range(100):
        assert bank.key_for(f"evil{i}") is None
    assert len(bank._keys) == 0  # misses never cached
    from simple_pbft_tpu.crypto import mac as mac_mod

    if not mac_mod.kx_available():
        # no X25519 backend: the committee publishes no kx keys and every
        # reply falls back to Ed25519 signatures — the known-peer half of
        # this test has nothing to exercise
        pytest.skip("cryptography wheel absent: MAC fast path disabled")
    known = bank.key_for("r0")
    assert known is not None and len(bank._keys) == 1


def test_mixed_split_triggers_early_rebroadcast():
    """One superseded + one real reply for the same ts (no quorum yet):
    the client rebroadcasts after a short backoff — well before
    request_timeout — and f+1 superseded replies then resolve the wait
    as SupersededError."""

    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(
            client_id="c0", cfg=cfg, seed=keys["c0"].seed, transport=t,
            request_timeout=5.0,
        )
        task = asyncio.create_task(client.submit("op", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        client._on_reply(Reply(sender="r0", view=0, seq=1, client_id="c0",
                               timestamp=ts, result="ok"))
        client._on_reply(Reply(sender="r1", view=0, seq=1, client_id="c0",
                               timestamp=ts, result="", superseded=1))
        # mixed split detected -> one rebroadcast lands after <=0.25 s
        await asyncio.sleep(0.5)
        assert len(t.broadcasts) == 1
        # a third conflicting reply must not schedule another one
        client._on_reply(Reply(sender="r2", view=0, seq=1, client_id="c0",
                               timestamp=ts, result="stale"))
        await asyncio.sleep(0.4)
        assert len(t.broadcasts) == 1
        # stabilized: a second superseded reply reaches f+1
        client._on_reply(Reply(sender="r3", view=0, seq=1, client_id="c0",
                               timestamp=ts, result="", superseded=1))
        with pytest.raises(SupersededError):
            await task

    run(scenario())
