"""An ACTIVE Byzantine committee member: equivocation under real traffic.

Unit tests cover individual hostile messages; this harness wires a
genuinely malicious replica — valid signatures, lying content — into a
live committee and asserts the two properties PBFT exists for:

- SAFETY: no two honest replicas execute different blocks at the same
  sequence (checked over every committed (seq, digest) pair).
- LIVENESS: client work keeps committing once failover moves past the
  equivocator (n=7 tolerates f=2).
"""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import Commit, Message, PrePrepare, Prepare, Request


class PassthroughTransport:
    """Base for Byzantine transport wrappers: subclasses override
    _mutate(raw) and/or broadcast."""

    def __init__(self, inner, signer: Signer):
        self.inner = inner
        self.signer = signer
        self.node_id = inner.node_id

    def _mutate(self, raw):
        return raw

    async def send(self, dest, raw):
        await self.inner.send(dest, self._mutate(raw))

    async def broadcast(self, raw, dests):
        await self.inner.broadcast(self._mutate(raw), dests)

    async def recv(self):
        return await self.inner.recv()

    def recv_nowait(self):
        return self.inner.recv_nowait()


class EquivocatingTransport(PassthroughTransport):
    """Pre-prepares are FORKED — half the committee receives the real
    block, the other half a validly-signed substitute with a different
    block — and half of its prepare votes lie about the digest (also
    validly signed)."""

    def __init__(self, inner, signer: Signer):
        super().__init__(inner, signer)
        self.forked = 0

    def _fork_pre_prepare(self, pp: PrePrepare) -> bytes:
        # the Byzantine node cannot forge CLIENT signatures, so the
        # strongest fork honest replicas will admit structurally is a
        # permuted/truncated block of already-signed requests
        block = list(reversed(pp.block))[: max(1, len(pp.block) - 1)]
        if block == pp.block:
            block = []
        forked = PrePrepare(
            view=pp.view, seq=pp.seq,
            digest=PrePrepare.block_digest(block), block=block,
        )
        self.signer.sign_msg(forked)
        return forked.to_wire()

    async def broadcast(self, raw, dests):
        try:
            msg = Message.from_wire(raw)
        except ValueError:
            msg = None
        if isinstance(msg, PrePrepare) and msg.block:
            forked_raw = self._fork_pre_prepare(msg)
            self.forked += 1
            for i, dest in enumerate(d for d in dests if d != self.node_id):
                await self.inner.send(dest, raw if i % 2 == 0 else forked_raw)
            return
        if isinstance(msg, Prepare) and self.forked % 2 == 1:
            lie = Prepare(view=msg.view, seq=msg.seq, digest="ff" * 32)
            self.signer.sign_msg(lie)
            raw = lie.to_wire()
        await self.inner.broadcast(raw, dests)


@pytest.mark.slow
def test_equivocating_primary_safety_and_liveness():
    async def main():
        c = LocalCommittee.build(n=7, clients=2, view_timeout=1.0)
        # r0 is the view-0 primary: make it Byzantine
        evil = c.replica("r0")
        evil.transport = EquivocatingTransport(
            evil.transport, Signer("r0", c.keys["r0"].seed)
        )
        for cl in c.clients:
            cl.request_timeout = 1.0
        c.start()
        t0 = time.perf_counter()
        ok = 0
        try:
            async def pump(cl, tag):
                nonlocal ok
                i = 0
                while time.perf_counter() - t0 < 30:
                    try:
                        r = await cl.submit(f"put {tag}{i} v{i}", retries=10)
                        ok += 1 if r == "ok" else 0
                    except (asyncio.TimeoutError, TimeoutError):
                        pass
                    i += 1

            await asyncio.gather(*(pump(cl, f"c{j}_")
                                   for j, cl in enumerate(c.clients)))
            await asyncio.sleep(1)
            honest = [r for r in c.replicas if r.id != "r0"]
            # SAFETY: one digest per committed seq across honest replicas
            by_seq = {}
            for r in honest:
                for seq, digest in r.committed_log.items():
                    by_seq.setdefault(seq, set()).add(digest)
                for s, d in r.checkpoint_digests.items():
                    by_seq.setdefault(("ckpt", s), set()).add(d)
            forks = {k: v for k, v in by_seq.items() if len(v) > 1}
            assert not forks, forks
            # LIVENESS: work committed despite the equivocating primary
            assert ok >= 20, ok
            # the equivocator really did equivocate
            assert evil.transport.forked >= 1
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


class SharePoisoningTransport(PassthroughTransport):
    """QC-mode Byzantine backup: ALL its votes (prepare and commit)
    carry a VALID Ed25519 signature and the correct digest, but a
    garbage-yet-on-curve BLS share — the poison only surfaces when the
    primary aggregates, forcing the bisection path under live traffic."""

    def __init__(self, inner, signer: Signer):
        super().__init__(inner, signer)
        self.poisoned = 0

    def _mutate(self, raw):
        try:
            msg = Message.from_wire(raw)
        except ValueError:
            return raw
        if isinstance(msg, (Prepare, Commit)) and getattr(
            msg, "bls_share", ""
        ):
            from simple_pbft_tpu.crypto import bls

            # a real G1 point that is NOT a share over the payload
            bogus = bls.sign(12345, b"not the payload")
            msg.bls_share = bogus.hex()
            self.signer.sign_msg(msg)
            self.poisoned += 1
            return msg.to_wire()
        return raw


@pytest.mark.slow
def test_qc_byzantine_share_poisoner_is_bisected_out():
    async def main():
        from simple_pbft_tpu.transport.local import FaultPlan

        # delay r3's traffic so the poisoner's votes are always within
        # the first 2f+1 the primary aggregates (otherwise the test's
        # bisection assertion would depend on scheduling luck)
        plan = FaultPlan(seed=3)
        c = LocalCommittee.build(n=4, clients=1, qc_mode=True,
                                 view_timeout=6.0, fault_plan=plan)
        real_deliver = c.net._deliver
        async def slow_r3(src, dst, raw):
            if src == "r3":
                await asyncio.sleep(0.15)
            await real_deliver(src, dst, raw)
        c.net._deliver = slow_r3
        evil = c.replica("r1")  # a BACKUP poisons its vote shares
        evil.transport = SharePoisoningTransport(
            evil.transport, Signer("r1", c.keys["r1"].seed)
        )
        c.clients[0].request_timeout = 8.0
        c.start()
        try:
            for i in range(3):
                assert await c.clients[0].submit(f"put p{i} {i}",
                                                 retries=10) == "ok"
            assert evil.transport.poisoned >= 1
            # the primary detected and excluded the poisoned shares
            primary = c.replica("r0")
            assert primary.metrics.get("qc_bad_shares", 0) >= 1, dict(
                primary.metrics
            )
            assert primary.metrics.get("qcs_formed", 0) >= 1
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
