"""An ACTIVE Byzantine committee member: equivocation under real traffic.

Unit tests cover individual hostile messages; this harness wires a
genuinely malicious replica — valid signatures, lying content — into a
live committee and asserts the two properties PBFT exists for:

- SAFETY: no two honest replicas execute different blocks at the same
  sequence (checked over every committed (seq, digest) pair).
- LIVENESS: client work keeps committing once failover moves past the
  equivocator (n=7 tolerates f=2).
"""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import Message, PrePrepare, Prepare, Request


class EquivocatingTransport:
    """Wraps a Byzantine replica's transport: pre-prepares are FORKED —
    half the committee receives the real block, the other half a
    validly-signed substitute with a different block — and half of its
    prepare votes lie about the digest (also validly signed)."""

    def __init__(self, inner, signer: Signer):
        self.inner = inner
        self.signer = signer
        self.node_id = inner.node_id
        self.forked = 0

    def _fork_pre_prepare(self, pp: PrePrepare) -> bytes:
        # the Byzantine node cannot forge CLIENT signatures, so the
        # strongest fork honest replicas will admit structurally is a
        # permuted/truncated block of already-signed requests
        block = list(reversed(pp.block))[: max(1, len(pp.block) - 1)]
        if block == pp.block:
            block = []
        forked = PrePrepare(
            view=pp.view, seq=pp.seq,
            digest=PrePrepare.block_digest(block), block=block,
        )
        self.signer.sign_msg(forked)
        return forked.to_wire()

    async def send(self, dest, raw):
        await self.inner.send(dest, raw)

    async def broadcast(self, raw, dests):
        try:
            msg = Message.from_wire(raw)
        except ValueError:
            msg = None
        if isinstance(msg, PrePrepare) and msg.block:
            forked_raw = self._fork_pre_prepare(msg)
            self.forked += 1
            for i, dest in enumerate(d for d in dests if d != self.node_id):
                await self.inner.send(dest, raw if i % 2 == 0 else forked_raw)
            return
        if isinstance(msg, Prepare) and self.forked % 2 == 1:
            lie = Prepare(view=msg.view, seq=msg.seq, digest="ff" * 32)
            self.signer.sign_msg(lie)
            raw = lie.to_wire()
        await self.inner.broadcast(raw, dests)

    async def recv(self):
        return await self.inner.recv()

    def recv_nowait(self):
        return self.inner.recv_nowait()


@pytest.mark.slow
def test_equivocating_primary_safety_and_liveness():
    async def main():
        c = LocalCommittee.build(n=7, clients=2, view_timeout=1.0)
        # r0 is the view-0 primary: make it Byzantine
        evil = c.replica("r0")
        evil.transport = EquivocatingTransport(
            evil.transport, Signer("r0", c.keys["r0"].seed)
        )
        for cl in c.clients:
            cl.request_timeout = 1.0
        c.start()
        t0 = time.perf_counter()
        ok = 0
        try:
            async def pump(cl, tag):
                nonlocal ok
                i = 0
                while time.perf_counter() - t0 < 30:
                    try:
                        r = await cl.submit(f"put {tag}{i} v{i}", retries=10)
                        ok += 1 if r == "ok" else 0
                    except (asyncio.TimeoutError, TimeoutError):
                        pass
                    i += 1

            await asyncio.gather(*(pump(cl, f"c{j}_")
                                   for j, cl in enumerate(c.clients)))
            await asyncio.sleep(1)
            honest = [r for r in c.replicas if r.id != "r0"]
            # SAFETY: one digest per committed seq across honest replicas
            by_seq = {}
            for r in honest:
                for seq, digest in r.committed_log:
                    by_seq.setdefault(seq, set()).add(digest)
                for s, d in r.checkpoint_digests.items():
                    by_seq.setdefault(("ckpt", s), set()).add(d)
            forks = {k: v for k, v in by_seq.items() if len(v) > 1}
            assert not forks, forks
            # LIVENESS: work committed despite the equivocating primary
            assert ok >= 20, ok
            # the equivocator really did equivocate
            assert evil.transport.forked >= 1
        finally:
            await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
