"""Native host-prep library (simple_pbft_tpu/native) vs Python oracles.

The C++ SHA-512 and sc_reduce must agree with hashlib / the pure-Python
RFC 8032 implementation on every input shape that matters: empty
messages, single-block, exact padding boundaries (111/112/128 bytes),
multi-block, and large buffers. If the toolchain is unavailable the
library falls back to Python — these tests then exercise the fallback.
"""

import hashlib

import numpy as np

from simple_pbft_tpu import native
from simple_pbft_tpu.crypto import ed25519_cpu as ref

# message lengths crossing all SHA-512 padding boundaries for the
# 64-byte (R||A) prefix: total = 64 + n, block = 128, len-field at 112
EDGE_LENS = [0, 1, 47, 48, 49, 63, 64, 65, 111, 112, 127, 128, 129, 1000, 5000]


def test_sha512_batch_matches_hashlib():
    rng = np.random.default_rng(7)
    msgs = [rng.integers(0, 256, n, dtype=np.uint8).tobytes() for n in EDGE_LENS]
    got = native.sha512_batch(msgs)
    for i, m in enumerate(msgs):
        assert got[i].tobytes() == hashlib.sha512(m).digest(), f"len {len(m)}"


def test_challenge_batch_matches_oracle():
    rng = np.random.default_rng(8)
    n = len(EDGE_LENS)
    r = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    a = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    msgs = [rng.integers(0, 256, ln, dtype=np.uint8).tobytes() for ln in EDGE_LENS]
    got = native.challenge_batch(r, a, msgs)
    for i in range(n):
        want = ref.challenge_scalar(r[i].tobytes(), a[i].tobytes(), msgs[i])
        assert got[i].tobytes() == want.to_bytes(32, "little"), f"row {i}"


def test_challenge_batch_random_bulk():
    rng = np.random.default_rng(9)
    n = 256
    r = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    a = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    msgs = [b"x" * int(i % 7) for i in range(n)]
    got = native.challenge_batch(r, a, msgs)
    for i in range(n):
        want = ref.challenge_scalar(r[i].tobytes(), a[i].tobytes(), msgs[i])
        assert int.from_bytes(got[i].tobytes(), "little") == want


def test_sc_reduce_boundary_values():
    """The signed-fold reduction's edge cases, driven directly: zero, the
    sign-flip magnitudes, values straddling L, 2^252, 2^253 and the
    512-bit top — each compared against Python bigint mod."""
    L = ref.L
    cases = [
        0, 1, 2, L - 1, L, L + 1, 2 * L, 2 * L - 1,
        2**252 - 1, 2**252, 2**252 + 1, 2**253 - 1, 2**253, 2**253 + 1,
        (2**512 - 1) // L * L,          # largest multiple of L in range
        (2**512 - 1) // L * L - 1,
        2**512 - 1, 2**511, 2**256 - 1, 2**256, 2**384 - 1,
        17 * L + 5, (2**260) * L % (2**512),
    ]
    rng = np.random.default_rng(11)
    cases += [int(rng.integers(0, 2**63)) * L for _ in range(8)]  # exact multiples
    digests = np.stack(
        [np.frombuffer(v.to_bytes(64, "little"), np.uint8) for v in cases]
    )
    got = native.sc_reduce_batch(digests)
    for i, v in enumerate(cases):
        assert int.from_bytes(got[i].tobytes(), "little") == v % L, f"case {i}: {v}"


def test_empty_batch():
    assert native.challenge_batch(
        np.zeros((0, 32), np.uint8), np.zeros((0, 32), np.uint8), []
    ).shape == (0, 32)
    assert native.sha512_batch([]).shape == (0, 64)
