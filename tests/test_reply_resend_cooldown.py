"""Cached-reply resend cooldown (round-4 reply-flood fix).

A retrying client's broadcast made every replica resend its cached reply
at once; duplicates inside a 1 s window are now squelched per
(client, ts). These tests pin: first resend immediate, in-window
duplicates dropped (metric counted), post-window retry answered again.
"""

import asyncio

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.messages import Message, Reply, Request
from simple_pbft_tpu.sim import sim_run


class CapturingTransport:
    def __init__(self, node_id):
        self.node_id = node_id
        self.sent = []

    async def send(self, dest, raw):
        self.sent.append((dest, raw))

    async def broadcast(self, raw, dests):
        pass


def run(coro, timeout=30):
    # virtual clock (ISSUE 13 satellite): the cooldown window is a real
    # timer now testable by SLEEPING through it (virtually, instantly)
    # instead of reaching into the replica's cooldown map
    return sim_run(asyncio.wait_for(coro, timeout))


def test_cached_reply_resend_cooldown():
    async def scenario():
        com = LocalCommittee.build(n=4)
        rep = com.replica("r1")
        cap = CapturingTransport("r1")
        rep.transport = cap
        client = com.clients[0]
        # simulate an executed request: cached reply present
        cached = Reply(view=0, seq=3, client_id="c0", timestamp=7, result="ok")
        rep.recent_replies["c0"] = {7: cached}
        req = Request(client_id="c0", timestamp=7, operation="put k v")
        client.signer.sign_msg(req)

        await rep._on_request(req)  # first retry: answered immediately
        assert len(cap.sent) == 1
        msg = Message.from_wire(cap.sent[0][1])
        assert isinstance(msg, Reply) and msg.result == "ok"

        await rep._on_request(req)  # duplicate inside the window: squelched
        await rep._on_request(req)
        assert len(cap.sent) == 1
        assert rep.metrics["reply_resend_squelched"] == 2

        await asyncio.sleep(1.2)  # virtual: age the 1 s window out
        await rep._on_request(req)  # next retry wave: answered again
        assert len(cap.sent) == 2
        # and the squelch re-engages inside the fresh window
        await rep._on_request(req)
        assert len(cap.sent) == 2
        assert rep.metrics["reply_resend_squelched"] == 3

        await com.stop()

    run(scenario())
