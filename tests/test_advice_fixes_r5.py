"""Round-5 ADVICE satellite fixes (ISSUE 1).

- OpenSSLVerifier's parsed-key cache stops inserting at MAX_KEYS instead
  of clearing: committee keys stay resident under adversarial fresh-key
  churn (mirrors NativeEdVerifier._row_for's policy).
- ops/comb.negate_rows fails loudly with RuntimeError (not a stripped
  assert) when called on packed-layout tables.
- chip_daemon logs each malformed queue-override spec once per file
  version, not once per queue poll.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# OpenSSLVerifier key-cache policy
# ---------------------------------------------------------------------------


class _FakeParsedKey:
    def __init__(self, raw):
        self.raw = raw

    def verify(self, sig, msg):
        if sig != msg:
            raise ValueError("bad")


def _openssl_with_fake_loader(max_keys):
    """OpenSSLVerifier with the `cryptography` loader mocked so the
    cache POLICY is testable on hosts without the wheel (this container:
    the wheel is absent and the real __init__ would ImportError)."""
    from simple_pbft_tpu.crypto.verifier import BatchItem, OpenSSLVerifier

    v = OpenSSLVerifier.__new__(OpenSSLVerifier)
    loads = []

    def load(raw):
        loads.append(raw)
        return _FakeParsedKey(raw)

    v._load = load
    v._cache = {}
    v.MAX_KEYS = max_keys
    return v, loads, BatchItem


def test_openssl_cache_stops_inserting_at_cap_keeps_committee_keys():
    v, loads, BatchItem = _openssl_with_fake_loader(max_keys=4)
    committee = [bytes([i]) * 32 for i in range(4)]
    # committee keys land early and fill the cache
    v.verify_batch([BatchItem(pk, b"m", b"m") for pk in committee])
    assert sorted(v._cache) == sorted(committee)
    # adversarial churn: 50 fresh keys — none may enter, none may evict
    churn = [bytes([100 + i]) * 32 for i in range(50)]
    out = v.verify_batch([BatchItem(pk, b"m", b"m") for pk in churn])
    assert out == [True] * 50  # still verified, just not cached
    assert sorted(v._cache) == sorted(committee)  # keys stayed resident
    # committee traffic after the storm: zero new parses (cache hits)
    n_loads = len(loads)
    v.verify_batch([BatchItem(pk, b"m2", b"m2") for pk in committee])
    assert len(loads) == n_loads


def test_openssl_cache_churn_costs_attacker_not_committee():
    v, loads, BatchItem = _openssl_with_fake_loader(max_keys=2)
    a, b = b"\x01" * 32, b"\x02" * 32
    v.verify_batch([BatchItem(a, b"m", b"m"), BatchItem(b, b"m", b"m")])
    # the same over-cap key re-parses per batch (bounded memory), the
    # resident keys never do
    evil = b"\xee" * 32
    for _ in range(3):
        v.verify_batch([BatchItem(evil, b"m", b"m"), BatchItem(a, b"m", b"m")])
    assert loads.count(evil) == 3
    assert loads.count(a) == 1


# ---------------------------------------------------------------------------
# comb.negate_rows packed-layout guard
# ---------------------------------------------------------------------------


def test_negate_rows_raises_runtime_error_on_packed_layout():
    """Must be an unconditional RuntimeError: under `python -O` a bare
    assert would vanish and packed tables would be dense-negated into
    wrong group elements (wrong verify verdicts) silently."""
    from simple_pbft_tpu.ops import comb

    comb.use_row_packing(True)
    try:
        with pytest.raises(RuntimeError, match="dense-layout"):
            comb.negate_rows(np.zeros((comb.ROW, 2), dtype=np.int32))
    finally:
        comb.use_row_packing(False)
    # dense layout still works (shape sanity only; numeric behavior is
    # covered by the kernel-vs-oracle suites)
    rows = np.asarray(comb.base_table())
    assert comb.negate_rows(rows).shape == rows.shape


# ---------------------------------------------------------------------------
# chip_daemon: malformed override spec logs once per file version
# ---------------------------------------------------------------------------


def test_override_spec_logged_once_per_file_version(tmp_path, monkeypatch):
    import chip_daemon

    override = tmp_path / "chip_queue_test.json"
    logged = []
    monkeypatch.setattr(chip_daemon, "QUEUE_OVERRIDE", str(override))
    monkeypatch.setattr(chip_daemon, "_log", lambda msg: logged.append(msg))
    chip_daemon._override_complained.clear()

    # one good spec + one malformed (args not a list)
    override.write_text(json.dumps([
        {"exp": "ok_exp", "kind": "consensus", "args": ["--configs", "1"]},
        {"exp": "bad_exp", "kind": "consensus", "args": "not-a-list"},
    ]))
    for _ in range(5):  # five queue polls
        out = chip_daemon._override_experiments()
        assert [e["exp"] for e in out] == ["ok_exp"]
    assert len(logged) == 1  # malformed spec complained about ONCE
    assert "bad_exp" in logged[0]

    # editing the file re-arms the complaint (new version, new log line)
    os.utime(override, (1, 1))  # distinct mtime stamp
    chip_daemon._override_experiments()
    assert len(logged) == 2

    # unreadable file: same once-per-version rule
    override.write_text("{not json")
    chip_daemon._override_experiments()
    chip_daemon._override_experiments()
    assert len(logged) == 3
    assert "unreadable" in logged[2]
