"""pbftlint self-tests (ISSUE 8): each checker fires on its minimal
positive fixture, stays silent on the negative twin, and the
suppression/baseline plumbing holds the zero-NEW-findings contract.

Fixture sources live in tests/lint_fixtures/ — they are parsed by the
linter, never imported."""

import json
import subprocess
import sys

import pytest

from tools.pbftlint import core
from tools.pbftlint.core import LintConfig, run_lint

FIX = "tests/lint_fixtures"


def run(*names, baseline=None, **kw):
    cfg = LintConfig(
        paths=tuple(f"{FIX}/{n}" for n in names),
        baseline_path=baseline,
        **kw,
    )
    return run_lint(cfg)


def codes(res):
    return [f.code for f in res["findings"]]


# ---------------------------------------------------------------------------
# PBL001 loop-blocking
# ---------------------------------------------------------------------------


def test_loop_blocking_positive():
    res = run("loop_pos.py")
    found = res["findings"]
    assert codes(res) == ["PBL001"] * 3
    details = {f.detail for f in found}
    assert "time.sleep" in details  # direct + transitive both present
    assert "json.loads" in details  # the per-tick re-decode shape
    # the transitive case names the loop-resident chain
    scopes = {f.scope for f in found}
    assert "helper" in scopes  # sync fn, resident only via async caller()


def test_loop_blocking_negative():
    res = run("loop_neg.py")
    assert codes(res) == []


def test_loop_blocking_suppression():
    res = run("loop_suppressed.py")
    # justified disable honored; bare disable converts to PBL000
    assert codes(res) == ["PBL000"]
    assert len(res["suppressed"]) == 2


# ---------------------------------------------------------------------------
# PBL002 determinism
# ---------------------------------------------------------------------------


def test_determinism_positive():
    res = run("det_pos.py")
    details = {f.detail for f in res["findings"]}
    assert set(codes(res)) == {"PBL002"}
    assert details == {
        "hash()", "random.random", "time.time", "set-iteration"
    }


def test_determinism_negative():
    res = run("det_neg.py")
    assert codes(res) == []


def test_determinism_scope_is_opt_in():
    # the same nondeterminism OUTSIDE a deterministic module is fine:
    # loop_neg.py has no marker and calls time-related functions freely
    res = run("loop_neg.py")
    assert "PBL002" not in codes(res)


# ---------------------------------------------------------------------------
# PBL003 drift
# ---------------------------------------------------------------------------


def test_drift_positive():
    res = run("drift_pos_a.py", "drift_pos_b.py")
    assert codes(res) == ["PBL003"]
    f = res["findings"][0]
    # the MIRROR flags, pointing at the origin (sorted-path order)
    assert f.path.endswith("drift_pos_b.py")
    assert "drift_pos_a" in f.detail


def test_drift_negative_alias_and_small_numeric():
    res = run("drift_neg_a.py", "drift_neg_b.py")
    assert codes(res) == []


def test_drift_needs_two_modules():
    res = run("drift_pos_a.py")
    assert codes(res) == []


# ---------------------------------------------------------------------------
# PBL004 exception-safety / PBL005 assert ban
# ---------------------------------------------------------------------------


def test_telemetry_guard_positive():
    res = run("telem_pos.py")
    assert codes(res) == ["PBL004"]
    assert res["findings"][0].detail == "tracer.flush_all"


def test_telemetry_guard_negative():
    res = run("telem_neg.py")
    assert codes(res) == []


def test_assert_ban_positive_and_negative():
    assert codes(run("assert_pos.py")) == ["PBL005"]
    assert codes(run("assert_neg.py")) == []


# ---------------------------------------------------------------------------
# PBL006 shape-stability
# ---------------------------------------------------------------------------


def test_shape_stray_jit_positive():
    res = run("shape_stray_pos.py")
    assert codes(res) == ["PBL006"]
    assert res["findings"][0].detail == "stray-jit:jax.jit"


def test_shape_unrecorded_dispatch_positive():
    res = run("shape_dispatch_pos.py")
    assert codes(res) == ["PBL006"]
    assert res["findings"][0].detail == "unrecorded-dispatch:self._fn"


def test_shape_negative():
    res = run("shape_neg.py")
    assert codes(res) == []


def test_shape_nested_record_does_not_satisfy_outer():
    """A _record_shape in a nested callback must not launder the outer
    dispatch, and the finding appears exactly once (not re-reported for
    the nested scope)."""
    res = run("shape_nested_pos.py")
    assert codes(res) == ["PBL006"]
    assert res["findings"][0].scope == "Verifier.outer"


def test_shape_devledger_record_does_not_launder_dispatch():
    """ISSUE 14 dispatch-recording seam: a devledger.record() in the
    dispatch body counts the pass's COST but does not keep
    post_warm_compiles honest — only _record_shape does, so the
    ledger-only positive must still flag and the full seam (both calls,
    the tpu_verifier shape) must pass clean."""
    res = run("shape_devledger_pos.py")
    assert codes(res) == ["PBL006"]
    assert res["findings"][0].detail == "unrecorded-dispatch:self._fn"
    assert codes(run("shape_devledger_neg.py")) == []


# ---------------------------------------------------------------------------
# baseline + suppression plumbing
# ---------------------------------------------------------------------------


def test_baseline_absorbs_known_findings(tmp_path):
    noisy = run("assert_pos.py")
    key = noisy["findings"][0].key
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "accepted": [{"key": key, "why": "fixture: documented invariant"}]
    }))
    res = run("assert_pos.py", baseline=str(bl))
    assert codes(res) == []
    assert len(res["baselined"]) == 1
    assert res["errors"] == []


def test_baseline_entry_without_why_is_an_error(tmp_path):
    noisy = run("assert_pos.py")
    key = noisy["findings"][0].key
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"accepted": [{"key": key, "why": ""}]}))
    res = run("assert_pos.py", baseline=str(bl))
    # the why-less entry is rejected: the finding stays NEW and the
    # format error is reported (CLI exits nonzero on either)
    assert codes(res) == ["PBL005"]
    assert any("no why" in e for e in res["errors"])


def test_stale_baseline_entries_surface(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "accepted": [{"key": "PBL005:gone.py::assert@x", "why": "fixed"}]
    }))
    res = run("assert_neg.py", baseline=str(bl))
    assert res["stale_baseline"] == ["PBL005:gone.py::assert@x"]


def test_finding_keys_are_line_stable():
    """The baseline key must not change when code moves within a file."""
    res = run("assert_pos.py")
    f = res["findings"][0]
    assert str(f.line) not in f.key.split(":", 2)[-1]
    assert f.key == f"PBL005:{FIX}/assert_pos.py::assert@len(batch) > 0"


def test_changed_only_filters_by_git(monkeypatch):
    monkeypatch.setattr(
        core, "changed_files", lambda root: [f"{FIX}/assert_pos.py"]
    )
    res = run("assert_pos.py", "telem_pos.py", changed_only=True)
    assert codes(res) == ["PBL005"]  # telem_pos finding filtered out


def test_unused_bare_disable_still_flags():
    """A why-less disable with no matching finding is dead policy, not
    a free pass — PBL000 sweeps every module."""
    res = run("bare_disable_unused.py")
    assert codes(res) == ["PBL000"]
    assert res["suppressed"] == []  # it suppressed nothing


def test_write_baseline_preserves_existing_whys(tmp_path):
    """--write-baseline must only add TODOs for NEW keys — curated
    justifications survive the rewrite."""
    noisy = run("assert_pos.py")
    key = noisy["findings"][0].key
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "accepted": [{"key": key, "why": "curated: kernel invariant"}]
    }))
    core.write_baseline(str(bl), noisy["findings"])
    doc = json.loads(bl.read_text())
    assert doc["accepted"][0]["key"] == key
    assert doc["accepted"][0]["why"] == "curated: kernel invariant"


def test_write_baseline_ignores_changed_filter(tmp_path, monkeypatch):
    """--write-baseline must capture the FULL scope even with --changed:
    a filtered write would omit new findings in unchanged files and
    drop their curation on the rewrite."""
    monkeypatch.setattr(core, "changed_files", lambda root: [])
    bl = tmp_path / "baseline.json"
    rc = core.main(
        [f"{FIX}/assert_pos.py", "--changed", "--write-baseline",
         "--baseline", str(bl)]
    )
    assert rc == 0
    doc = json.loads(bl.read_text())
    assert any(e["key"].startswith("PBL005:") for e in doc["accepted"])


def test_cli_exits_nonzero_on_stale_baseline(tmp_path):
    """The CLI and the CI gate (stale_baseline == []) must agree: a
    pre-commit run with a stale entry fails, same as CI would."""
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "accepted": [{"key": "PBL005:gone.py::assert@x", "why": "fixed"}]
    }))
    rc = core.main(
        [f"{FIX}/assert_neg.py", "--baseline", str(bl)]
    )
    assert rc == 1


def test_changed_files_includes_untracked(tmp_path):
    """A brand-new unstaged module must appear in --changed scope —
    that is exactly where new findings are born."""
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True)
    subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
         "-c", "user.name=t", "commit", "-q", "--allow-empty",
         "-m", "seed"],
        check=True,
    )
    (tmp_path / "tracked.py").write_text("x = 1\n")
    subprocess.run(
        ["git", "-C", str(tmp_path), "add", "tracked.py"], check=True
    )
    subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
         "-c", "user.name=t", "commit", "-q", "-m", "one"],
        check=True,
    )
    (tmp_path / "tracked.py").write_text("x = 2\n")  # working-tree edit
    (tmp_path / "fresh.py").write_text("assert x\n")  # untracked
    got = core.changed_files(str(tmp_path))
    assert got == ["fresh.py", "tracked.py"]


# ---------------------------------------------------------------------------
# the repo gate itself
# ---------------------------------------------------------------------------


def test_repo_is_clean_against_checked_in_baseline():
    """Acceptance criterion: `python -m tools.pbftlint --json` exits 0
    on the repo. Runs in-process (subprocess would re-pay jax import)."""
    res = run_lint(LintConfig())
    assert [f.to_doc() for f in res["findings"]] == []
    assert res["errors"] == []
    assert res["stale_baseline"] == []
    assert res["files_analyzed"] > 30


def test_checked_in_baseline_every_entry_justified():
    with open(core.DEFAULT_BASELINE) as fh:
        doc = json.load(fh)
    assert doc["accepted"], "baseline exists and is non-trivial"
    for ent in doc["accepted"]:
        assert ent.get("why", "").strip(), f"unjustified: {ent.get('key')}"


def test_cli_json_shape():
    out = subprocess.run(
        [sys.executable, "-m", "tools.pbftlint", "--json",
         f"{FIX}/assert_pos.py", "--no-baseline"],
        capture_output=True, text=True, cwd=core.REPO_ROOT, timeout=120,
    )
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["findings"][0]["code"] == "PBL005"
    assert doc["findings"][0]["key"].startswith("PBL005:")


def test_cli_exit_zero_on_clean_fixture():
    out = subprocess.run(
        [sys.executable, "-m", "tools.pbftlint", "--json",
         f"{FIX}/assert_neg.py", "--no-baseline"],
        capture_output=True, text=True, cwd=core.REPO_ROOT, timeout=120,
    )
    assert out.returncode == 0
    assert json.loads(out.stdout)["findings"] == []


# ---------------------------------------------------------------------------
# audited-entry existence binding (PBL004's rename tripwire)
# ---------------------------------------------------------------------------


def test_audited_entries_bound_to_real_defs():
    """Every AUDITED_NO_RAISE target must exist in its owning module —
    renaming RequestTracer.emit must break the lint, not silently
    un-protect every call site. The full-repo run above would surface
    an audited-missing finding; assert the table's targets directly so
    the failure names the entry."""
    from tools.pbftlint.checks import exception_safety as es

    mods = {
        m.path: m
        for m in core.collect_modules(LintConfig())
    }
    for (root, term), (owner, cls, name) in es.AUDITED_NO_RAISE.items():
        mod = mods.get(owner)
        assert mod is not None, f"audited owner module missing: {owner}"
        assert es._def_exists(mod, cls, name), (
            f"audited entry ({root}.{term}) -> {owner}:{cls}.{name} "
            "no longer exists; re-audit and update AUDITED_NO_RAISE"
        )


# ---------------------------------------------------------------------------
# PBL007 clock seam (ISSUE 13)
# ---------------------------------------------------------------------------


def test_clock_seam_positive():
    res = run("clock_pos.py")
    assert set(codes(res)) == {"PBL007"}
    details = {f.detail for f in res["findings"]}
    assert details == {
        "time.monotonic", "time.perf_counter", "time.time",
        "asyncio.sleep", "loop.time",
    }


def test_clock_seam_negative():
    # seam-compliant forms pass; the call_at idiom rides a justified
    # suppression (counted, not a finding)
    res = run("clock_neg.py")
    assert codes(res) == []
    assert len(res["suppressed"]) == 1


def test_clock_seam_scope_is_opt_in():
    # raw clocks OUTSIDE a clock-injectable module are not PBL007's
    # business (engine/tool modules measure; they don't run timers the
    # simulation must control)
    res = run("loop_neg.py")
    assert "PBL007" not in codes(res)


def test_clock_seam_covers_the_injectable_surface():
    """The scoped module list must keep naming the modules the sim
    runtime actually drives — deleting one from the checker would
    silently un-gate its timers."""
    from tools.pbftlint.checks import clock_seam

    assert set(clock_seam.SCOPED) >= {
        "simple_pbft_tpu/consensus/replica.py",
        "simple_pbft_tpu/consensus/statesync.py",
        "simple_pbft_tpu/client.py",
        "simple_pbft_tpu/telemetry.py",
        "simple_pbft_tpu/faults.py",
    }
