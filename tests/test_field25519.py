"""Field/point kernels vs. the exact-integer CPU oracle.

The jnp limb arithmetic (ops/field25519.py, ops/edwards.py) must agree with
Python bignum math on every operation — these are known-answer tests over
random and adversarial (boundary) inputs, run on the 8-virtual-device CPU
backend (conftest.py) exactly as they jit on TPU.

Device layout convention: limb axis FIRST, batch axes trailing — a batch
of field elements is (17, n), a batch of points (4, 17, n).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.ops import edwards as ed
from simple_pbft_tpu.ops import field25519 as fe

P = ref.P
rng = random.Random(1234)

BOUNDARY = [0, 1, 2, 19, P - 1, P - 2, P - 19, 2**255 - 19 - 1, 2**254, ref.D]


def limbs(v: int) -> jnp.ndarray:
    return jnp.asarray(fe._int_to_limbs_np(v % P))


def limb_batch(vals) -> jnp.ndarray:
    """ints -> (17, n) limb-first batch."""
    return jnp.asarray(np.stack([fe._int_to_limbs_np(v % P) for v in vals], axis=1))


def unlimbs(a) -> int:
    return fe._limbs_to_int_np(np.asarray(a))


def rand_elems(n):
    return [rng.randrange(P) for _ in range(n)]


class TestFieldOps:
    def test_roundtrip(self):
        for v in BOUNDARY + rand_elems(20):
            assert unlimbs(limbs(v)) == v % P

    def test_bytes32_to_limbs_window_extraction(self):
        # the uint64-window fast path must agree with direct bit math on
        # the low 255 bits (bit 255, the sign bit, excluded)
        import numpy as np

        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, (50, 32), dtype=np.uint8)
        data[0, :] = 0xFF  # all-ones boundary
        data[1, :] = 0
        out = fe.bytes32_to_limbs_major_np(data)
        assert out.shape == (fe.NLIMB, 50)
        for j in range(50):
            v = int.from_bytes(bytes(data[j]), "little") & ((1 << 255) - 1)
            assert fe._limbs_to_int_np(out[:, j : j + 1]) == v

    def test_nibbles_major_layout(self):
        import numpy as np

        from simple_pbft_tpu.ops import comb

        rng = np.random.default_rng(8)
        data = rng.integers(0, 256, (20, 32), dtype=np.uint8)
        out = comb.nibbles_major_np(data)
        assert out.shape == (comb.NPOS, 20)
        for j in range(20):
            v = int.from_bytes(bytes(data[j]), "little")
            got = sum(int(out[i, j]) << (4 * i) for i in range(comb.NPOS))
            assert got == v

    def test_two_p_constant_encodes_2p(self):
        # _two_p builds 2p from scalars (Pallas kernels must not capture
        # array constants); pin it against the exact integer
        import numpy as np

        tp = np.asarray(fe._two_p(jnp.zeros((fe.NLIMB, 1), jnp.int32)))
        assert fe._limbs_to_int_np(tp) == 2 * fe.P_INT

    def test_add_sub_mul(self):
        vals = BOUNDARY + rand_elems(30)
        b_vals = list(reversed(vals))
        a, b = limb_batch(vals), limb_batch(b_vals)
        add = jax.jit(fe.add)(a, b)
        sub = jax.jit(fe.sub)(a, b)
        mul = jax.jit(fe.mul)(a, b)
        for i, (x, y) in enumerate(zip(vals, b_vals)):
            assert unlimbs(fe.to_canonical(add[:, i])) == (x + y) % P
            assert unlimbs(fe.to_canonical(sub[:, i])) == (x - y) % P
            assert unlimbs(fe.to_canonical(mul[:, i])) == (x * y) % P

    def test_mul_impls_agree(self):
        vals = BOUNDARY + rand_elems(10)
        a, b = limb_batch(vals), limb_batch(list(reversed(vals)))
        skew = jax.jit(fe.mul_skew)(a, b)
        padacc = jax.jit(fe.mul_padacc)(a, b)
        for i in range(len(vals)):
            assert unlimbs(fe.to_canonical(skew[:, i])) == unlimbs(
                fe.to_canonical(padacc[:, i])
            )

    def test_mul_worst_case_limbs(self):
        # all-ones limbs (maximum column sums) must not overflow int32
        top = jnp.asarray(np.full(fe.NLIMB, fe.MASK, dtype=np.int32))
        for mul in (fe.mul_padacc, fe.mul_skew):
            got = fe.to_canonical(mul(top, top))
            assert unlimbs(got) == (((1 << 255) - 1) ** 2) % P

    def test_invert(self):
        vals = [0, 1, 2, P - 1] + rand_elems(5)
        batch = limb_batch(vals)
        out = jax.jit(fe.invert)(batch)
        for i, v in enumerate(vals):
            want = pow(v, P - 2, P) if v else 0
            assert unlimbs(fe.to_canonical(out[:, i])) == want

    def test_pow22523(self):
        vals = [1, 2] + rand_elems(5)
        batch = limb_batch(vals)
        out = jax.jit(fe.pow22523)(batch)
        for i, v in enumerate(vals):
            assert unlimbs(fe.to_canonical(out[:, i])) == pow(v, (P - 5) // 8, P)

    def test_eq_parity_zero(self):
        a = limbs(5)
        b = fe.add(limbs(P - 1), limbs(6))  # 5 via wraparound
        assert bool(fe.eq(a, b))
        assert not bool(fe.eq(a, limbs(6)))
        assert bool(fe.is_zero(fe.sub(a, b)))
        for v in [0, 1, 2, P - 1] + rand_elems(5):
            assert int(fe.parity(limbs(v))) == v % 2


def pt(p_int):
    return jnp.asarray(ed._point_const(p_int))


def pt_batch(pts):
    """points -> (4, 17, n)."""
    return jnp.asarray(np.stack([ed._point_const(p) for p in pts], axis=-1))


def affine(p) -> tuple:
    x, y, z, t = [unlimbs(fe.to_canonical(p[i])) for i in range(4)]
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


class TestPointOps:
    def rand_point(self):
        k = rng.randrange(ref.L)
        return ref.point_mul(k, ref.B), k

    def test_add_double(self):
        p_ref, _ = self.rand_point()
        q_ref, _ = self.rand_point()
        got = affine(jax.jit(ed.point_add)(pt(p_ref), pt(q_ref)))
        assert got == ref.point_to_affine(ref.point_add(p_ref, q_ref))
        got = affine(jax.jit(ed.point_double)(pt(p_ref)))
        assert got == ref.point_to_affine(ref.point_double(p_ref))

    def test_add_identity_cases(self):
        p_ref, _ = self.rand_point()
        ident = jnp.asarray(ed.IDENTITY)
        assert affine(ed.point_add(pt(p_ref), ident)) == ref.point_to_affine(p_ref)
        assert affine(ed.point_add(ident, ident)) == (0, 1)
        assert affine(ed.point_double(ident)) == (0, 1)
        # P + (-P) = identity
        assert affine(ed.point_add(pt(p_ref), ed.point_neg(pt(p_ref)))) == (0, 1)

    def test_double_scalar_mul(self):
        # batched (trailing dim 3): one compile covers all cases
        qs = []
        for _ in range(3):
            q_ref, _ = self.rand_point()
            qs.append((q_ref, rng.randrange(ref.L), rng.randrange(ref.L)))
        q_arr = pt_batch([q for q, _, _ in qs])
        s_bits = jnp.asarray(
            [[(s >> (255 - i)) & 1 for _, s, _ in qs] for i in range(256)],
            dtype=jnp.int32,
        )  # (256, 3) — bit axis leading
        k_bits = jnp.asarray(
            [[(k >> (255 - i)) & 1 for _, _, k in qs] for i in range(256)],
            dtype=jnp.int32,
        )
        got = jax.jit(ed.double_scalar_mul_base)(s_bits, k_bits, q_arr)
        for i, (q_ref, s, k) in enumerate(qs):
            want = ref.point_add(ref.point_mul(s, ref.B), ref.point_mul(k, q_ref))
            assert affine(got[:, :, i]) == ref.point_to_affine(want)

    def test_compress_decompress_roundtrip(self):
        pts = [self.rand_point()[0] for _ in range(4)]
        wires = np.stack(
            [np.frombuffer(ref.point_compress(p), dtype=np.uint8) for p in pts]
        )
        y_limbs = jnp.asarray(fe.bytes32_to_limbs_np(wires).T)  # (17, n)
        sign = jnp.asarray(fe.sign_bits_np(wires))
        point, ok = jax.jit(ed.decompress)(y_limbs, sign)
        y_out, x_par = jax.jit(ed.compress)(point)
        for i, p_ref in enumerate(pts):
            enc = int.from_bytes(wires[i].tobytes(), "little")
            assert bool(ok[i])
            assert affine(point[:, :, i]) == ref.point_to_affine(p_ref)
            assert unlimbs(y_out[:, i]) == enc & ((1 << 255) - 1)
            assert int(x_par[i]) == enc >> 255

    def test_decompress_invalid(self):
        ys = list(range(2, 14))
        y_arr = limb_batch(ys)
        zero_sign = jnp.zeros(len(ys), dtype=jnp.int32)
        _, ok = jax.jit(ed.decompress)(y_arr, zero_sign)
        flags = [ref._recover_x(y, 0) is not None for y in ys]
        assert any(not f for f in flags)  # some non-residues in range
        for i, f in enumerate(flags):
            assert bool(ok[i]) == f

    def test_decompress_zero_x_sign(self):
        # y = 1 -> x = 0; sign bit 1 must be rejected (non-canonical)
        y_arr = limb_batch([1, 1])
        signs = jnp.asarray([1, 0], dtype=jnp.int32)
        _, ok = jax.jit(ed.decompress)(y_arr, signs)
        assert not bool(ok[0])
        assert bool(ok[1])
