"""Cross-replica trace plane (ISSUE 20): the unsigned wire envelope
stamps without perturbing signatures or canonical frames, quorum-arrival
order statistics attribute margins and stragglers, identical seeded sim
runs emit byte-identical joined ledgers, the NTP-style skew solver
recovers injected offsets exactly, slot_trace's distributed path
reconciles against measured commit_ms within the 5% acceptance bound,
the Perfetto export round-trips with paired async wire events, and the
committed floors reference both passes an honest ledger and fails a
doctored one (the canary contract)."""

from __future__ import annotations

import copy
import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from simple_pbft_tpu import clock, spans, trace  # noqa: E402
from simple_pbft_tpu.messages import Message, PrePrepare, Prepare  # noqa: E402
from simple_pbft_tpu.sim import Scenario, run_scenario  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


span_ledger = _load_tool("span_ledger")
slot_trace = _load_tool("slot_trace")
critical_path = _load_tool("critical_path")
bench_gate = _load_tool("bench_gate")
pbft_top = _load_tool("pbft_top")

RECON_BOUND = 0.05  # ISSUE 20 acceptance: |path - measured| / measured


# ---------------------------------------------------------------------------
# wire envelope


@pytest.fixture()
def stamping():
    trace.configure(True)
    yield
    trace.configure(False)


class TestEnvelope:
    def test_stamp_preserves_decoded_message(self, stamping):
        """The envelope is unsigned metadata: a stamped frame must
        decode to the exact same message fields as the unstamped one
        (Message._build drops unknown top-level keys)."""
        msg = Prepare(view=2, seq=7, digest="ab" * 32, sender="r3")
        raw = msg.to_wire()
        stamped = trace.stamp(raw, trace.PREPARE, 2, 7, "r3")
        assert stamped != raw
        assert trace._GATE in stamped
        assert (Message.from_wire(stamped).to_dict()
                == Message.from_wire(raw).to_dict())

    def test_stamped_frame_stays_canonical(self, stamping):
        """Splicing at the sorted key position keeps the frame valid
        canonical JSON — re-encoding reproduces the exact bytes."""
        pp = PrePrepare(view=0, seq=1, digest="cd" * 32, block=[],
                        sender="r0")
        stamped = trace.stamp(pp.to_wire(), trace.PREPREPARE, 0, 1, "r0")
        canon = json.dumps(
            json.loads(stamped), sort_keys=True, separators=(",", ":")
        ).encode()
        assert canon == stamped

    def test_extract_fields_and_span_counter(self, stamping):
        msg = Prepare(view=4, seq=9, digest="ee" * 32, sender="r5")
        stamped = trace.stamp(msg.to_wire(), trace.PREPARE, 4, 9, "r5")
        env = trace.extract(stamped)
        assert env is not None
        assert env["p"] == "prepare" and env["v"] == 4 and env["q"] == 9
        assert env["s"] == "r5" and isinstance(env["t"], int)
        # configure() reset the per-sender counter: first stamp is span 0
        assert env["i"] == 0
        again = trace.stamp(msg.to_wire(), trace.PREPARE, 4, 9, "r5")
        assert trace.extract(again)["i"] == 1

    def test_stamp_idempotent(self, stamping):
        msg = Prepare(view=1, seq=2, digest="aa" * 32, sender="r1")
        stamped = trace.stamp(msg.to_wire(), trace.PREPARE, 1, 2, "r1")
        assert trace.stamp(stamped, trace.PREPARE, 1, 2, "r1") == stamped

    def test_disabled_is_byte_noop(self):
        trace.configure(False)
        raw = Prepare(view=1, seq=2, digest="aa" * 32).to_wire()
        assert trace.stamp(raw, trace.PREPARE, 1, 2, "r1") is raw
        assert trace.extract(raw) is None

    def test_recv_stamp_emits_complete_edge_doc(self, stamping, tmp_path):
        ledger_path = tmp_path / "r9.spans.jsonl"
        spans.configure("r9", str(ledger_path))
        try:
            msg = Prepare(view=3, seq=11, digest="bb" * 32, sender="r3")
            stamped = trace.stamp(msg.to_wire(), trace.PREPARE, 3, 11, "r3")
            trace.recv_stamp("r9", stamped)       # cross-node: one edge
            trace.recv_stamp("r3", stamped)       # self-delivery: skipped
            trace.recv_stamp("r9", msg.to_wire())  # unstamped: no-op
        finally:
            spans.configure("", None)
        docs = [json.loads(ln) for ln in
                ledger_path.read_text().splitlines() if ln.strip()]
        edges = [d for d in docs if d.get("evt") == "edge"]
        assert len(edges) == 1
        e = edges[0]
        assert e["src"] == "r3" and e["node"] == "r9"
        assert e["phase"] == "prepare" and e["view"] == 3 and e["seq"] == 11
        assert isinstance(e["t_send_us"], int)
        assert isinstance(e["t_recv_us"], int)


# ---------------------------------------------------------------------------
# quorum-arrival order statistics


class TestQuorumStats:
    @pytest.fixture()
    def vclock(self, monkeypatch):
        t = {"v": 0.0}
        monkeypatch.setattr(clock, "now", lambda: t["v"])
        return t

    def test_margin_straggler_and_arrival_order(self, vclock, tmp_path):
        ledger_path = tmp_path / "r0.spans.jsonl"
        spans.configure("r0", str(ledger_path))
        try:
            qs = trace.QuorumStats("r0")
            for t_s, sender in ((0.001, "r1"), (0.002, "r2"), (0.005, "r3")):
                vclock["v"] = t_s
                qs.note_vote("prepare", 0, 1, sender)
            qs.note_quorum("prepare", 0, 1, quorum=3, n=4)
            vclock["v"] = 0.009
            qs.note_vote("prepare", 0, 1, "r0")   # straggler: all n seen
        finally:
            spans.configure("", None)
        snap = qs.snapshot()
        assert snap["certs"] == 1 and snap["open"] == 0
        # margin = slowest - (2f+1)-th = 9ms - 5ms
        assert snap["last_margin_ms"] == pytest.approx(4.0)
        assert snap["last_straggler"] == "r0"
        doc = [json.loads(ln) for ln in
               ledger_path.read_text().splitlines()
               if '"quorum"' in ln][0]
        assert doc["order"] == ["r1", "r2", "r3", "r0"]
        assert doc["votes"] == 4 and doc["quorum"] == 3

    def test_duplicate_votes_first_arrival_wins(self, vclock):
        qs = trace.QuorumStats("r0")
        vclock["v"] = 0.001
        qs.note_vote("commit", 0, 2, "r1")
        vclock["v"] = 0.009
        qs.note_vote("commit", 0, 2, "r1")   # retransmit: ignored
        vclock["v"] = 0.002
        qs.note_vote("commit", 0, 2, "r2")
        vclock["v"] = 0.003
        qs.note_vote("commit", 0, 2, "r3")
        qs.note_quorum("commit", 0, 2, quorum=3, n=3)
        snap = qs.snapshot()
        assert snap["certs"] == 1
        assert snap["last_straggler"] == "r3"
        assert snap["last_margin_ms"] == pytest.approx(0.0)

    def test_partial_cert_never_reaching_quorum(self, vclock):
        """A QC-mode backup sees no vote flood: flush must count the
        cert as partial, emit no margin, and never raise."""
        qs = trace.QuorumStats("r1")
        vclock["v"] = 0.001
        qs.note_vote("prepare", 0, 3, "r2")
        qs.flush_all()
        snap = qs.snapshot()
        assert snap["certs"] == 0 and snap["partial"] == 1
        assert snap["open"] == 0

    def test_flush_upto_watermark(self, vclock):
        qs = trace.QuorumStats("r0")
        for seq in (1, 2, 5):
            vclock["v"] = 0.001 * seq
            qs.note_vote("prepare", 0, seq, "r1")
        qs.flush_upto(2)
        assert len(qs._open) == 1   # seq 5 survives the watermark


# ---------------------------------------------------------------------------
# clock-skew solver


def _edge(src, dst, t_true_us, lat_us, theta):
    """One synthetic edge: per-node clocks read true time + theta."""
    return {
        "evt": "edge", "phase": "prepare", "view": 0, "seq": 1,
        "src": src, "node": dst,
        "t_send_us": t_true_us + theta[src],
        "t_recv_us": t_true_us + lat_us + theta[dst],
    }


class TestSkewSolver:
    def test_recovers_injected_offsets_exactly(self):
        """Nodes with known clock offsets and a symmetric 1000us floor
        latency: the solver must return the exact corrections that land
        every timestamp on the reference node's clock."""
        theta = {"a": 0.0, "b": 5000.0, "c": -3000.0}
        edges = []
        t = 0.0
        for src, dst in (("a", "b"), ("b", "a"), ("b", "c"), ("c", "b")):
            # one floor-latency frame per direction plus jittered ones
            for jitter in (0.0, 740.0, 260.0):
                edges.append(_edge(src, dst, t, 1000.0 + jitter, theta))
                t += 10_000.0
        sk = slot_trace.solve_offsets(edges)
        assert sk["reference"] == "a"
        assert sk["offset_us"] == {"a": 0.0, "b": -5000.0, "c": 3000.0}
        assert sk["unanchored"] == []
        assert sk["pairs"]["a<->b"]["rtt_min_us"] == pytest.approx(2000.0)
        # corrected one-way latency is the true floor again
        e = edges[0]
        corrected = ((e["t_recv_us"] + sk["offset_us"]["b"])
                     - (e["t_send_us"] + sk["offset_us"]["a"]))
        assert corrected == pytest.approx(1000.0)

    def test_one_way_traffic_stays_unanchored(self):
        """Without return traffic latency and offset cannot be split —
        the solver must report the pair unanchored, not guess."""
        theta = {"a": 0.0, "b": 5000.0}
        edges = [_edge("a", "b", 0.0, 1000.0, theta)]
        sk = slot_trace.solve_offsets(edges)
        assert set(sk["unanchored"]) == {"a", "b"}
        assert sk["pairs"] == {}


# ---------------------------------------------------------------------------
# end-to-end on the sim (virtual clock, signatures off => deterministic)


@pytest.fixture(scope="module")
def traced_runs(tmp_path_factory):
    dirs = []
    for tag in ("a", "b"):
        d = str(tmp_path_factory.mktemp(f"trace_{tag}"))
        sc = Scenario(seed=5, n=4, clients=2, requests=10,
                      spec="shape=wan3dc", verify_signatures=False,
                      trace_dir=d)
        res = run_scenario(sc, wall_timeout=120.0)
        assert res.ok, res.failure
        dirs.append(d)
    return dirs


@pytest.fixture(scope="module")
def analysis(traced_runs):
    ledger = span_ledger.load_ledger(span_ledger.discover(traced_runs[0]))
    return ledger, slot_trace.analyze(ledger)


class TestSimTracePlane:
    def test_joined_trace_byte_deterministic(self, traced_runs):
        """Two runs of the identical seeded scenario must write
        byte-identical span ledgers: every persisted doc rides the
        virtual clock and per-sender span counters reset per run."""
        a, b = (open(os.path.join(d, "sim.spans.jsonl"), "rb").read()
                for d in traced_runs)
        assert a and a == b

    def test_virtual_clock_offsets_solve_to_zero(self, analysis):
        _, an = analysis
        assert an["skew"]["unanchored"] == []
        assert all(v == 0.0 for v in an["skew"]["offset_us"].values())
        assert len(an["skew"]["pairs"]) > 0

    def test_reconciliation_within_acceptance_bound(self, analysis):
        _, an = analysis
        rec = an["reconciliation"]
        assert rec["slots"] > 0
        assert rec["err_p50"] <= RECON_BOUND
        assert rec["err_p99"] <= RECON_BOUND

    def test_decomposition_names_dominant_edge(self, analysis):
        _, an = analysis
        assert an["slots"] > 0 and an["edges"] > 0
        for d in an["decomposition"]:
            assert d["dominant"] in slot_trace.SEGMENTS
            assert sum(d["shares"].values()) == pytest.approx(1.0, abs=0.02)
            assert (d["wire_share"] + d["compute_share"]
                    == pytest.approx(1.0, abs=1e-6))

    def test_quorum_docs_well_formed(self, analysis):
        ledger, an = analysis
        assert an["quorum"]["certs"] > 0
        assert 0.0 < an["quorum"]["straggler_share"] <= 1.0
        for q in ledger["quorum"]:
            assert len(q["order"]) == q["votes"] >= q["quorum"]
            assert q["margin_ms"] >= 0.0
            assert q["straggler"] == q["order"][-1]

    def test_edges_causal_on_shared_clock(self, analysis):
        ledger, _ = analysis
        assert all(e["t_recv_us"] >= e["t_send_us"]
                   for e in ledger["edge"])

    def test_perfetto_export_roundtrip(self, analysis):
        ledger, an = analysis
        doc = json.loads(json.dumps(
            slot_trace.perfetto_export(ledger, an["skew"]["offset_us"]),
            sort_keys=True,
        ))
        events = doc["traceEvents"]
        names = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert names == set(an["nodes"])
        for e in events:
            if e["ph"] == "X":
                assert isinstance(e["ts"], float)
                assert e["dur"] >= 0.0
        begins = {e["id"] for e in events if e["ph"] == "b"}
        ends = {e["id"] for e in events if e["ph"] == "e"}
        assert begins and begins == ends


# ---------------------------------------------------------------------------
# shared loader + schema stamps (the ISSUE 20 small fix)


class TestSharedLoader:
    def test_both_tools_stamp_the_shared_schema_version(self, analysis):
        ledger, an = analysis
        cp = critical_path.analyze(ledger["span"])
        assert (cp["schema_version"] == an["schema_version"]
                == span_ledger.LEDGER_SCHEMA_VERSION)

    def test_load_ledger_tolerates_torn_lines(self, tmp_path):
        p = tmp_path / "x.spans.jsonl"
        span = {"evt": "span", "stage": "phase.execute", "node": "r0",
                "seq": 1, "view": 0, "dur_ms": 1.0, "t_mono": 2.0}
        edge = {"evt": "edge", "phase": "prepare", "view": 0, "seq": 1,
                "src": "r1", "node": "r0", "t_send_us": 1, "t_recv_us": 2}
        p.write_text(json.dumps(span) + "\n"
                     + '{"evt": "edge", "torn' + "\n"
                     + json.dumps(edge) + "\n")
        led = span_ledger.load_ledger([str(p)])
        assert len(led["span"]) == 1 and len(led["edge"]) == 1
        assert span_ledger.load_spans([str(p)]) == led["span"]


# ---------------------------------------------------------------------------
# bench_gate trace.* rows + the committed floors reference


def _reference_lines():
    path = os.path.join(ROOT, "bench_results", "trace_ci_reference.jsonl")
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


class TestBenchGateTraceRows:
    def test_trace_metrics_registered(self):
        for metric in ("trace.quorum_margin_p50_ms", "trace.straggler_share",
                       "trace.reconciliation_err_p50",
                       "trace.reconciliation_err_p99"):
            assert metric in bench_gate.METRICS

    def test_reference_passes_its_own_measurement(self):
        ref = _reference_lines()
        fresh = copy.deepcopy(ref)
        for d in fresh:
            d.pop("gate", None)
            d.pop("gate_mode", None)
        assert bench_gate.run_gate(fresh, ref)["ok"]

    def test_doctored_line_canary_must_fail(self):
        """The committed floors are real floors: push the reconciliation
        error past gate.max and the gate MUST go red."""
        ref = _reference_lines()
        doctored = copy.deepcopy(ref)
        for d in doctored:
            d.pop("gate", None)
            d.pop("gate_mode", None)
        doctored[0]["trace"]["reconciliation_err_p50"] = 0.5
        rep = bench_gate.run_gate(doctored, ref)
        assert not rep["ok"]
        assert any(r["metric"] == "trace.reconciliation_err_p50"
                   for r in rep["regressions"])

    def test_data_volume_floor_catches_empty_plane(self):
        starved = copy.deepcopy(_reference_lines())
        for d in starved:
            d.pop("gate", None)
            d.pop("gate_mode", None)
        starved[0]["trace"]["certs"] = 10
        assert not bench_gate.run_gate(starved, _reference_lines())["ok"]

    def test_bench_line_shape(self, analysis):
        _, an = analysis
        line = slot_trace.bench_line(an, "cellname")
        assert line["cell"] == "cellname"
        for k in ("quorum_margin_p50_ms", "quorum_margin_p99_ms",
                  "straggler_share", "reconciliation_err_p50",
                  "reconciliation_err_p99", "certs", "slots"):
            assert k in line["trace"]


# ---------------------------------------------------------------------------
# pbft_top TRACE column


class TestTopColumn:
    def test_trace_cell_formats_margin_and_straggler(self):
        snap = {"replica": {"quorum": {
            "certs": 3, "margin_ms": {"p50": 3.246}, "last_straggler": "r7",
        }}}
        assert pbft_top.trace_cell(snap) == "3.2!r7"

    def test_trace_cell_blank_before_first_cert(self):
        assert pbft_top.trace_cell({}) == ""
        assert pbft_top.trace_cell(
            {"replica": {"quorum": {"certs": 0}}}) == ""

    def test_trace_column_registered(self):
        assert "TRACE" in pbft_top.COLUMNS
