"""BLS12-381 + quorum-certificate tests.

The pairing implementation is anchored ALGEBRAICALLY (no external test
vectors exist in this environment): generator orders, tower-field inverse
round-trips, untwist-lands-on-curve, bilinearity e(aP,bQ) = e(P,Q)^{ab},
and image order r. A wrong constant or formula breaks at least one of
these. On top: the signature scheme's accept/reject matrix, aggregation
soundness, proof-of-possession, and the QC helpers' structural checks.

Pairings cost ~0.8 s each in pure Python — tests budget them carefully
(the process-wide memo in consensus/qc.py is also under test).
"""

import random

import pytest

from simple_pbft_tpu.consensus import qc as qc_mod
from simple_pbft_tpu.crypto import bls
from simple_pbft_tpu.messages import QuorumCert, qc_payload

rng = random.Random(42)


# ---------------------------------------------------------------------------
# field towers
# ---------------------------------------------------------------------------


def _rand_f2():
    return (rng.randrange(bls.P), rng.randrange(bls.P))


def _rand_f6():
    return (_rand_f2(), _rand_f2(), _rand_f2())


def _rand_f12():
    return (_rand_f6(), _rand_f6())


def test_tower_inverses_roundtrip():
    for _ in range(3):
        x2 = _rand_f2()
        assert bls.f2_mul(x2, bls.f2_inv(x2)) == bls.F2_ONE
        x6 = _rand_f6()
        assert bls.f6_mul(x6, bls.f6_inv(x6)) == bls.F6_ONE
        x12 = _rand_f12()
        assert bls.f12_mul(x12, bls.f12_inv(x12)) == bls.F12_ONE


def test_f6_v_mul_consistent():
    # multiplying by v via the rotation helper == multiplying by (0,1,0)
    x = _rand_f6()
    v = (bls.F2_ZERO, bls.F2_ONE, bls.F2_ZERO)
    assert bls.f6_mul_v(x) == bls.f6_mul(x, v)


# ---------------------------------------------------------------------------
# curve + pairing algebra
# ---------------------------------------------------------------------------


def test_generators_on_curve_with_order_r():
    assert bls.G1.is_on_curve(bls.G1_GEN)
    assert bls.G2.is_on_curve(bls.G2_GEN)
    assert bls.G1.mul_pt(bls.G1_GEN, bls.R_ORDER - 1) == bls.G1.neg_pt(bls.G1_GEN)
    assert bls.G2.mul_pt(bls.G2_GEN, bls.R_ORDER - 1) == bls.G2.neg_pt(bls.G2_GEN)


def test_untwist_lands_on_fp12_curve():
    q = bls._untwist(bls.G2_GEN)
    x, y = q
    lhs = bls.f12_mul(y, y)
    rhs = bls.f12_add_el(bls.f12_mul(bls.f12_mul(x, x), x), bls._embed_fp(4))
    assert lhs == rhs


def test_pairing_bilinearity():
    e = bls.pairing(bls.G1_GEN, bls.G2_GEN)
    assert e != bls.F12_ONE
    assert bls.f12_pow(e, bls.R_ORDER) == bls.F12_ONE  # image order r
    e23 = bls.pairing(
        bls.G1.mul_pt(bls.G1_GEN, 2), bls.G2.mul_pt(bls.G2_GEN, 3)
    )
    assert e23 == bls.f12_pow(e, 6)


def test_hash_to_g1_in_subgroup_and_deterministic():
    p1 = bls.hash_to_g1(b"vote payload")
    p2 = bls.hash_to_g1(b"vote payload")
    assert p1 == p2
    assert bls.G1.is_on_curve(p1)
    assert bls._subgroup_check_g1(p1)
    assert bls.hash_to_g1(b"other") != p1
    # domain separation: same bytes, different tag -> different point
    assert bls.hash_to_g1(b"vote payload", bls.DST_POP) != p1


# ---------------------------------------------------------------------------
# signature scheme
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def keys():
    return [bls.keygen(bytes([i + 1]) * 32) for i in range(4)]


def test_sign_verify_reject_matrix(keys):
    (sk0, pk0), (sk1, pk1) = keys[0], keys[1]
    msg = b"commit view=1 seq=9"
    sig = bls.sign(sk0, msg)
    assert bls.verify(pk0, msg, sig)
    assert not bls.verify(pk1, msg, sig)  # wrong key
    assert not bls.verify(pk0, b"forged", sig)  # wrong msg
    flipped = bytearray(sig)
    flipped[5] ^= 1
    assert not bls.verify(pk0, msg, bytes(flipped))  # corrupted point
    assert not bls.verify(pk0, msg, b"\x00" * bls.G1_BYTES)  # infinity
    assert not bls.verify(b"junk", msg, sig)  # malformed pubkey


def test_aggregate_and_pop(keys):
    msg = b"qc payload"
    sigs = [bls.sign(sk, msg) for sk, _ in keys]
    pks = [pk for _, pk in keys]
    agg = bls.aggregate_signatures(sigs)
    assert bls.verify_aggregate(pks, msg, agg)
    assert not bls.verify_aggregate(pks[:3], msg, agg)  # signer set mismatch
    assert not bls.verify_aggregate(pks, b"other", agg)
    assert not bls.verify_aggregate([], msg, agg)
    sk0, pk0 = keys[0]
    pop = bls.pop_prove(sk0, pk0)
    assert bls.pop_verify(pk0, pop)
    assert not bls.pop_verify(keys[1][1], pop)


def test_native_sign_bit_identical_to_python(monkeypatch):
    """Ed25519-style determinism: the native sign/keygen path must emit
    byte-identical signatures and pubkeys to the bigint path (same
    hash-to-G1, same scalar multiple, same canonical serialization)."""
    from simple_pbft_tpu import native

    if not native.bls_available():
        pytest.skip("no native toolchain")
    seed = bytes([0x5A]) * 32
    msg = b"determinism probe"
    sk_n, pk_n = bls.keygen(seed)
    sig_n = bls.sign(sk_n, msg)
    pop_n = bls.pop_prove(sk_n, pk_n)

    class _NoNative:
        @staticmethod
        def bls_sign(*a, **k):
            return None

        @staticmethod
        def bls_pubkey(*a, **k):
            return None

        @staticmethod
        def bls_verify_one(*a, **k):
            return None

        @staticmethod
        def bls_verify_aggregate(*a, **k):
            return None

    monkeypatch.setattr(bls, "_native", lambda: _NoNative)
    sk_p, pk_p = bls.keygen(seed)
    assert (sk_n, pk_n) == (sk_p, pk_p)
    assert bls.sign(sk_p, msg) == sig_n
    assert bls.pop_prove(sk_p, pk_p) == pop_n


def test_native_and_python_paths_agree(keys, monkeypatch):
    """Differential check: the C++ pairing library (native/bls381.cpp)
    and this module's bigint path must return identical verdicts on
    valid, forged, corrupted, and malformed inputs."""
    from simple_pbft_tpu import native

    if not native.bls_available():
        pytest.skip("no native toolchain")
    msg = b"differential payload"
    sigs = [bls.sign(sk, msg) for sk, _ in keys]
    pks = [pk for _, pk in keys]
    agg = bls.aggregate_signatures(sigs)
    corrupt = bytearray(agg)
    corrupt[7] ^= 2
    sk0, pk0 = keys[0]
    pop = bls.pop_prove(sk0, pk0)
    s0 = bls.sign(sk0, msg)
    # on-curve but OUT of the r-subgroup (no cofactor clearing): the one
    # input class where the two subgroup-check implementations differ
    # structurally (ZeroDivisionError catch vs mid-ladder fail flag)
    x = 0
    while True:
        x += 1
        y2 = (x * x * x + 4) % bls.P
        y = pow(y2, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == y2:
            nonsub = (x, y)
            if not bls._subgroup_check_g1(nonsub):
                break
    nonsub_sig = bls._g1_to_bytes(nonsub)

    def run_all():
        return [
            bls.verify_aggregate(pks, msg, nonsub_sig),
            bls.verify(pk0, msg, nonsub_sig),
            bls.verify_aggregate(pks, msg, agg),
            bls.verify_aggregate(pks[:2], msg, agg),
            bls.verify_aggregate(pks, b"forged", agg),
            bls.verify_aggregate(pks, msg, bytes(corrupt)),
            bls.verify_aggregate(pks, msg, b"\x00" * bls.G1_BYTES),
            bls.verify(pk0, msg, s0),
            bls.verify(keys[1][1], msg, s0),
            bls.pop_verify(pk0, pop),
            bls.pop_verify(keys[1][1], pop),
        ]

    native_results = run_all()

    class _NoNative:
        @staticmethod
        def bls_verify_one(*a, **k):
            return None

        @staticmethod
        def bls_verify_aggregate(*a, **k):
            return None

    monkeypatch.setattr(bls, "_native", lambda: _NoNative)
    python_results = run_all()
    assert native_results == python_results
    assert native_results[0] is False and native_results[1] is False
    assert native_results[2] is True and native_results[7] is True


# ---------------------------------------------------------------------------
# QC helpers
# ---------------------------------------------------------------------------


class _Cfg:
    def __init__(self, keys):
        self.bls = {f"r{i}": pk for i, (_, pk) in enumerate(keys)}
        self.quorum = 3
        self.replica_ids = tuple(sorted(self.bls))

    def bls_pubkey(self, nid):
        return self.bls.get(nid)


def test_qc_build_verify_and_cache(keys):
    cfg = _Cfg(keys)
    shares = {
        f"r{i}": qc_mod.sign_share(sk, "prepare", 2, 7, "d" * 64)
        for i, (sk, _) in enumerate(keys[:3])
    }
    cert = qc_mod.build_qc("prepare", 2, 7, "d" * 64, shares, cfg.quorum)
    assert cert is not None
    assert qc_mod.verify_qc(cfg, cert)
    # memo: second call must hit the cache (same verdict, no recompute)
    assert qc_mod.verify_qc(cfg, cert)
    # structural rejects
    assert not qc_mod.verify_qc(
        cfg, QuorumCert(phase="bogus", view=2, seq=7, digest="d" * 64,
                        signers=cert.signers, agg_sig=cert.agg_sig)
    )
    assert not qc_mod.verify_qc(
        cfg, QuorumCert(phase="prepare", view=2, seq=7, digest="d" * 64,
                        signers=["r0", "r0", "r1"], agg_sig=cert.agg_sig)
    )
    assert not qc_mod.verify_qc(
        cfg, QuorumCert(phase="prepare", view=2, seq=7, digest="d" * 64,
                        signers=["r0", "r1", "rX"], agg_sig=cert.agg_sig)
    )
    # tampered digest -> pairing fails
    bad = QuorumCert(phase="prepare", view=2, seq=7, digest="e" * 64,
                     signers=cert.signers, agg_sig=cert.agg_sig)
    assert not qc_mod.verify_qc(cfg, bad)


def test_bisect_bad_shares(keys):
    cfg = _Cfg(keys)
    good = {
        f"r{i}": qc_mod.sign_share(sk, "commit", 0, 3, "a" * 64)
        for i, (sk, _) in enumerate(keys[:3])
    }
    shares = dict(good)
    shares["r1"] = qc_mod.sign_share(keys[1][0], "commit", 0, 4, "a" * 64)  # wrong seq
    surviving = qc_mod.bisect_bad_shares(cfg, "commit", 0, 3, "a" * 64, shares)
    assert set(surviving) == {"r0", "r2"}


def test_qc_payload_is_canonical():
    a = qc_payload("prepare", 1, 2, "d")
    b = qc_payload("prepare", 1, 2, "d")
    assert a == b
    assert qc_payload("commit", 1, 2, "d") != a
