"""View-change protocol: failover, certificates, O-set determinism.

The reference's view change is dead code (view.go, SURVEY.md §2 item 8);
these tests cover the full Castro-Liskov protocol this framework adds:
timer-driven failover, VIEW-CHANGE/NEW-VIEW certificate validation, the
f+1 join rule, prepared-state carryover, and adversarial certificates.
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.config import make_test_committee
from simple_pbft_tpu.consensus.viewchange import (
    compute_o_set,
    validate_new_view,
    validate_view_change,
)
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import (
    Checkpoint,
    NewView,
    PrePrepare,
    Prepare,
    Request,
    ViewChange,
)


def _run(coro):
    return asyncio.run(coro)


async def _eventually(pred, timeout=10.0, tick=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(tick)
    return False


# ---------------------------------------------------------------------------
# End-to-end failover
# ---------------------------------------------------------------------------


def test_failover_after_primary_crash():
    """Primary dies; client work drives a view change; the request still
    executes under the new primary, on every surviving replica."""

    async def main():
        c = LocalCommittee.build(n=4, view_timeout=0.3)
        c.start()
        client = c.clients[0]
        client.request_timeout = 0.25
        assert await client.submit("put a 1") == "ok"

        # kill the view-0 primary
        c.replica("r0").kill()
        result = await client.submit("put b 2", retries=60)
        assert result == "ok"
        survivors = [r for r in c.replicas if r.id != "r0"]
        assert all(r.view >= 1 for r in survivors)
        assert await _eventually(
            lambda: all(
                r.app.data.get("b") == "2" for r in survivors
            )
        )
        # the committee keeps working in the new view
        assert await client.submit("get a", retries=60) == "1"
        await c.stop()

    _run(main())


def test_failover_after_stable_checkpoint():
    """Regression: a VIEW-CHANGE built after h > 0 must carry the 2f+1
    checkpoint certificate AT h (GC once deleted it, wedging failover)."""

    async def main():
        # 0.8 s timer / 0.5 s client: the assertion is BEHAVIORAL (the
        # post-checkpoint VIEW-CHANGE certificate works) — at 0.3/0.25 s
        # a saturated full-suite host stalls the loop past whole timer
        # periods and fails the submit patience spuriously
        c = LocalCommittee.build(n=4, view_timeout=0.8, checkpoint_interval=2)
        c.start()
        client = c.clients[0]
        client.request_timeout = 0.5
        for i in range(4):  # past two checkpoint intervals
            assert await client.submit(f"put k{i} {i}") == "ok"
        assert all(r.stable_seq > 0 for r in c.replicas)
        c.replica("r0").kill()
        assert await client.submit("put after 1", retries=60) == "ok"
        survivors = [r for r in c.replicas if r.id != "r0"]
        assert all(r.view >= 1 for r in survivors)
        # settle: submit resolves at f+1 replies, the third survivor
        # may still be executing under a loaded host
        assert await _eventually(
            lambda: all(r.app.data.get("after") == "1" for r in survivors),
            timeout=15, tick=0.25,
        )
        await c.stop()

    _run(main())


def test_cascaded_failover_two_primaries_down():
    """Views 0 and 1's primaries both dead: exponential backoff walks to
    view 2 and the committee (n=7, f=2) commits there."""

    async def main():
        c = LocalCommittee.build(n=7, view_timeout=0.25)
        c.start()
        client = c.clients[0]
        client.request_timeout = 0.25
        c.replica("r0").kill()
        c.replica("r1").kill()
        assert await client.submit("put x 9", retries=40) == "ok"
        survivors = [r for r in c.replicas if r.id not in ("r0", "r1")]
        assert all(r.view >= 2 for r in survivors)
        await c.stop()

    _run(main())


def test_prepared_request_survives_view_change():
    """A block prepared in view 0 but not committed (commits partitioned)
    must re-commit in view 1 with the same digest — no lost or forked
    decisions across the failover."""

    async def main():
        from simple_pbft_tpu.transport.local import FaultPlan

        plan = FaultPlan()
        c = LocalCommittee.build(n=4, view_timeout=0.4, fault_plan=plan)
        c.start()
        client = c.clients[0]
        client.request_timeout = 0.3
        assert await client.submit("put seed 1") == "ok"

        # cut the primary off from everyone (it can still receive) right
        # after its proposal wave: replicas prepare, commits can't quorum
        # at the client... simpler: cut commits by partitioning r0 fully
        # after a short delay — the request below will prepare via r0's
        # pre-prepare, then stall, then view-change.
        async def cut_soon():
            await asyncio.sleep(0.05)
            for peer in ("r1", "r2", "r3", "c0"):
                plan.cut("r0", peer)

        asyncio.get_running_loop().create_task(cut_soon())
        result = await client.submit("put y 7", retries=30)
        assert result == "ok"
        survivors = [c.replica(r) for r in ("r1", "r2", "r3")]
        # submit resolves at f+1 matching replies — settle so the
        # slowest survivor's execution doesn't race the assertion
        assert await _eventually(
            lambda: all(r.app.data.get("y") == "7" for r in survivors),
            timeout=15, tick=0.25,
        )
        snaps = {r.app.snapshot() for r in survivors}
        assert len(snaps) == 1  # no divergence
        await c.stop()

    _run(main())


# ---------------------------------------------------------------------------
# Certificate-level units
# ---------------------------------------------------------------------------


def _signed_vc(cfg, keys, sender, new_view, stable_seq=0, proofs=None, cps=None):
    vc = ViewChange(
        new_view=new_view,
        stable_seq=stable_seq,
        checkpoint_proof=cps or [],
        prepared_proofs=proofs or [],
    )
    Signer(sender, keys[sender].seed).sign_msg(vc)
    return vc


def _prepared_proof(cfg, keys, view, seq, op="noop"):
    req = Request(client_id="c0", timestamp=seq, operation=op)
    Signer("c0", keys["c0"].seed).sign_msg(req)
    block = [req.to_dict()]
    pp = PrePrepare(
        view=view, seq=seq, digest=PrePrepare.block_digest(block), block=block
    )
    Signer(cfg.primary(view), keys[cfg.primary(view)].seed).sign_msg(pp)
    prepares = []
    for rid in cfg.replica_ids[: cfg.quorum]:
        p = Prepare(view=view, seq=seq, digest=pp.digest)
        Signer(rid, keys[rid].seed).sign_msg(p)
        prepares.append(p.to_dict())
    return {"pre_prepare": pp.to_dict(), "prepares": prepares}, pp


def test_o_set_prefers_highest_view_and_fills_gaps():
    cfg, keys = make_test_committee(n=4)
    proof_v0, pp0 = _prepared_proof(cfg, keys, view=0, seq=2, op="old")
    proof_v1, pp1 = _prepared_proof(cfg, keys, view=1, seq=2, op="new")
    vcs = {
        "r1": _signed_vc(cfg, keys, "r1", 2, proofs=[proof_v0]),
        "r2": _signed_vc(cfg, keys, "r2", 2, proofs=[proof_v1]),
        "r3": _signed_vc(cfg, keys, "r3", 2),
    }
    h, o_set = compute_o_set(cfg, vcs, new_view=2)
    assert h == 0
    assert [seq for seq, _ in o_set] == [1, 2]
    # seq 1 is a gap -> no-op digest; seq 2 takes the view-1 certificate
    # (O is digest-only: blocks resolve at install from store/fetch)
    assert o_set[0][1] == PrePrepare.block_digest([])
    assert o_set[1][1] == pp1.digest


def test_validate_view_change_rejects_bad_certs():
    cfg, keys = make_test_committee(n=4)
    proof, _ = _prepared_proof(cfg, keys, view=0, seq=1)

    good = _signed_vc(cfg, keys, "r1", 1, proofs=[proof])
    assert validate_view_change(cfg, good) is not None

    # under-sized prepare certificate
    thin = {
        "pre_prepare": proof["pre_prepare"],
        "prepares": proof["prepares"][:1],
    }
    assert (
        validate_view_change(cfg, _signed_vc(cfg, keys, "r1", 1, proofs=[thin]))
        is None
    )

    # prepared proof from a view >= the target view is inadmissible
    future_proof, _ = _prepared_proof(cfg, keys, view=1, seq=1)
    assert (
        validate_view_change(
            cfg, _signed_vc(cfg, keys, "r1", 1, proofs=[future_proof])
        )
        is None
    )

    # stable_seq > 0 demands a checkpoint certificate
    assert (
        validate_view_change(cfg, _signed_vc(cfg, keys, "r1", 1, stable_seq=64))
        is None
    )

    # non-committee sender
    outsider = ViewChange(new_view=1)
    outsider.sender = "mallory"
    assert validate_view_change(cfg, outsider) is None


def test_validate_new_view_rejects_tampered_o_set():
    cfg, keys = make_test_committee(n=4)
    proof, pp = _prepared_proof(cfg, keys, view=0, seq=1, op="put k v")
    vcs = [
        _signed_vc(cfg, keys, rid, 1, proofs=[proof] if rid == "r1" else [])
        for rid in ("r1", "r2", "r3")
    ]
    new_primary = cfg.primary(1)

    def build_nv(slots):
        pps = []
        for seq, digest in slots:
            # re-issues are always detached (digest-only)
            npp = PrePrepare(view=1, seq=seq, digest=digest, block=[])
            Signer(new_primary, keys[new_primary].seed).sign_msg(npp)
            pps.append(npp.to_dict())
        nv = NewView(
            new_view=1,
            viewchange_proof=[v.to_dict() for v in vcs],
            pre_prepares=pps,
        )
        Signer(new_primary, keys[new_primary].seed).sign_msg(nv)
        return nv

    _, o_set = compute_o_set(cfg, {v.sender: v for v in vcs}, 1)
    assert validate_new_view(cfg, build_nv(o_set)) is not None

    # drop the prepared slot (primary trying to lose a prepared request)
    empty = [(1, PrePrepare.block_digest([]))]
    assert validate_new_view(cfg, build_nv(empty)) is None

    # wrong sender: only the new view's primary may install it
    nv = build_nv(o_set)
    imposter = "r2" if new_primary != "r2" else "r3"
    nv.sender = ""
    Signer(imposter, keys[imposter].seed).sign_msg(nv)
    assert validate_new_view(cfg, nv) is None


def test_checkpoint_proof_carries_watermark():
    """A VC claiming h > 0 with a valid 2f+1 checkpoint cert validates."""
    cfg, keys = make_test_committee(n=4)
    cps = []
    for rid in cfg.replica_ids[: cfg.quorum]:
        cp = Checkpoint(seq=64, state_digest="d" * 64)
        Signer(rid, keys[rid].seed).sign_msg(cp)
        cps.append(cp.to_dict())
    vc = _signed_vc(cfg, keys, "r1", 1, stable_seq=64, cps=cps)
    res = validate_view_change(cfg, vc)
    assert res is not None
    _, cp_msgs, items, _qcs = res
    assert len(cp_msgs) == 3 and len(items) == 3


def test_build_view_change_dedups_multi_view_prepared_state():
    """A seq prepared in two successive views (prepared in v, re-prepared
    via the O-set in v+1, not committed) must emit ONE prepared proof — the
    highest-view certificate — or validate_view_change rejects the whole
    VIEW-CHANGE and the replica livelocks in failover (advisor finding)."""

    async def main():
        c = LocalCommittee.build(n=4, view_timeout=0)  # timers off
        r = c.replica("r1")

        # prepare the same seq in view 0 and view 1 at this replica
        for view in (0, 1):
            proof, pp = _prepared_proof(c.cfg, c.keys, view=view, seq=5)
            inst = r._instance(view, 5)
            inst.on_pre_prepare(pp)
            for rd in proof["prepares"]:
                from simple_pbft_tpu.messages import Message

                inst.on_prepare(Message.from_dict(rd))
            assert inst.prepared()

        vc = r.vc.build_view_change(2)
        seqs = []
        for p in vc.prepared_proofs:
            pp = PrePrepare(**{
                k: v for k, v in p["pre_prepare"].items()
                if k in ("view", "seq", "digest", "block", "sender", "sig")
            })
            seqs.append((pp.seq, pp.view))
        assert seqs == [(5, 1)], seqs  # one proof, highest view wins
        Signer("r1", c.keys["r1"].seed).sign_msg(vc)
        assert validate_view_change(c.cfg, vc) is not None

    _run(main())


def test_wire_caps_are_per_type():
    """Certificate messages (ViewChange/NewView) get the large wire cap;
    data-plane messages keep the 8 MiB cap (advisor finding: a loaded
    primary's failover certificate must stay deliverable)."""
    from simple_pbft_tpu.messages import Message, Request

    big = "x" * (9 * 1024 * 1024)
    req = Request(client_id="c0", timestamp=1, operation=big)
    raw = req.to_wire()
    with pytest.raises(ValueError):
        Message.from_wire(raw)

    vc = ViewChange(new_view=1, stable_seq=0,
                    checkpoint_proof=[{"pad": big}], prepared_proofs=[])
    decoded = Message.from_wire(vc.to_wire())
    assert isinstance(decoded, ViewChange)


def test_vc_replay_buffer_feeds_window_laggards():
    """NEW-VIEW pre-prepares beyond a lagging replica's watermark window
    are buffered at install and replayed once the window advances —
    without the buffer the replica silently skips those slots forever
    (advisor finding). Also: entries from superseded views are dropped."""

    async def main():
        c = LocalCommittee.build(n=4, view_timeout=0, watermark_window=4)
        r = c.replica("r1")
        assert r.stable_seq == 0  # window is (0, 4]

        # a certificate pre-prepare beyond the window (seq 7, view 0)
        _, pp_beyond = _prepared_proof(c.cfg, c.keys, view=0, seq=7)
        # and one from a view this replica will never be in
        _, pp_stale = _prepared_proof(c.cfg, c.keys, view=3, seq=6)
        r.vc_replay[7] = pp_beyond
        r.vc_replay[6] = pp_stale

        # window still lags: replay must keep the in-view entry buffered
        await r._replay_vc_buffer()
        assert 7 in r.vc_replay
        assert 6 not in r.vc_replay  # superseded view dropped
        assert (0, 7) not in r.instances

        # state transfer advances the stable checkpoint; the buffered
        # pre-prepare must now be consumed into a live instance
        r.stable_seq = 4
        await r._replay_vc_buffer()
        assert 7 not in r.vc_replay
        inst = r.instances.get((0, 7))
        assert inst is not None and inst.pre_prepare is not None

    _run(main())


def test_detached_newview_block_fetched_by_laggard():
    """Digest-only failover end to end: a backup that never saw the
    original pre-prepare (no block behind the re-issued digest) must
    FETCH the block from peers after the NEW-VIEW and install the slot
    with the exact original content."""

    async def main():
        c = LocalCommittee.build(n=4, view_timeout=0)  # timers off
        c.start()
        try:
            proof, pp = _prepared_proof(c.cfg, c.keys, view=0, seq=1,
                                        op="put fetched 1")
            original_block = pp.block
            # r1 and r2 admit the original pre-prepare (block lands in
            # their stores); r3 never sees it
            from simple_pbft_tpu.messages import Message

            for rid in ("r1", "r2"):
                r = c.replica(rid)
                await r.on_phase_msg(pp)
                assert pp.digest in r.block_store
            # r1 holds a full prepared certificate for the slot
            r1 = c.replica("r1")
            for rd in proof["prepares"]:
                await r1.on_phase_msg(Message.from_dict(rd))

            # the new view's primary (r1) collects 2f+1 VIEW-CHANGEs:
            # its own (carries the digest-only prepared proof) + r2 + r3
            await r1.vc.start_view_change(1)
            assert c.cfg.primary(1) == "r1"
            for rid in ("r2", "r3"):
                await r1.vc.on_view_change(
                    _signed_vc(c.cfg, c.keys, rid, 1)
                )
            # NEW-VIEW broadcast -> r3 installs, lacks the block, fetches
            r3 = c.replica("r3")
            for _ in range(100):
                if r3.metrics.get("blocks_fetched", 0) >= 1:
                    break
                await asyncio.sleep(0.05)
            assert r3.metrics.get("blocks_fetched", 0) >= 1, dict(r3.metrics)
            inst = r3.instances.get((1, 1))
            assert inst is not None and inst.pre_prepare is not None
            assert inst.pre_prepare.block == original_block
            assert inst.pre_prepare.digest == pp.digest
        finally:
            await c.stop()

    _run(main())


def test_new_view_dedups_checkpoint_proofs_and_shrinks_wire():
    """ISSUE 3 satellite (VERDICT weak #5: 237-419 KB NEW-VIEWs): the
    2f+1 embedded VIEW-CHANGEs all prove the same h with the same
    checkpoint certificate — the NEW-VIEW ships ONE pooled copy, every
    stripped VC refills from the pool at validation, and the envelope
    signatures still verify (the proof is detached from them)."""
    from simple_pbft_tpu.consensus.viewchange import dedup_checkpoint_proofs
    from simple_pbft_tpu.crypto.verifier import best_cpu_verifier
    from simple_pbft_tpu.messages import Message

    cfg, keys = make_test_committee(n=4)
    cps = []
    for rid in cfg.replica_ids[: cfg.quorum]:
        cp = Checkpoint(seq=64, state_digest="d" * 64)
        Signer(rid, keys[rid].seed).sign_msg(cp)
        cps.append(cp.to_dict())
    vcs = [
        _signed_vc(cfg, keys, rid, 1, stable_seq=64, cps=cps)
        for rid in ("r1", "r2", "r3")
    ]
    vc_dicts, pool = dedup_checkpoint_proofs(vcs)
    assert len(pool) == 1 and pool[0]["seq"] == 64
    assert all(d["checkpoint_proof"] == [] for d in vc_dicts)
    # the originals keep their proofs (dedup works on dict copies)
    assert all(vc.checkpoint_proof for vc in vcs)

    new_primary = cfg.primary(1)
    nv = NewView(
        new_view=1, viewchange_proof=vc_dicts, pre_prepares=[],
        checkpoint_pool=pool,
    )
    Signer(new_primary, keys[new_primary].seed).sign_msg(nv)
    # size regression: 3 proof copies -> 1 pooled copy must cut the
    # certificate roughly in third (the proofs dominate this NEW-VIEW)
    inline = NewView(
        new_view=1, viewchange_proof=[v.to_dict() for v in vcs],
        pre_prepares=[],
    )
    Signer(new_primary, keys[new_primary].seed).sign_msg(inline)
    assert len(nv.to_wire()) < 0.6 * len(inline.to_wire())

    # full wire round trip -> validation refills and accepts
    nv2 = Message.from_wire(nv.to_wire())
    res = validate_new_view(cfg, nv2)
    assert res is not None
    vcs_out, items, _qcs = res
    assert set(vcs_out) == {"r1", "r2", "r3"}
    # refilled: each validated VC carries the full proof again
    assert all(len(vc.checkpoint_proof) == cfg.quorum for vc in vcs_out.values())
    # every nested signature (3 VC envelopes over DETACHED-proof
    # payloads + 3 checkpoints per refilled proof) actually verifies
    assert len(items) == 3 + 3 * cfg.quorum
    assert all(best_cpu_verifier().verify_batch(items))

    # a pool entry for an h nobody claims is rejected structurally
    bad = NewView(
        new_view=1, viewchange_proof=vc_dicts, pre_prepares=[],
        checkpoint_pool=pool + [{"seq": 64, "proof": []}],  # dup seq
    )
    Signer(new_primary, keys[new_primary].seed).sign_msg(bad)
    assert validate_new_view(cfg, Message.from_wire(bad.to_wire())) is None

    # stripped VC with NO pool entry for its h must reject whole
    naked = NewView(
        new_view=1, viewchange_proof=vc_dicts, pre_prepares=[],
        checkpoint_pool=[],
    )
    Signer(new_primary, keys[new_primary].seed).sign_msg(naked)
    assert validate_new_view(cfg, Message.from_wire(naked.to_wire())) is None
