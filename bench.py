"""Benchmark: batched Ed25519 verification throughput on the attached chip.

Headline metric (BASELINE.md): Ed25519 verifies/sec on one chip; target is
>= 1,000,000/s (`vs_baseline` is value / 1e6 — the reference itself verifies
zero signatures, SURVEY.md §6, so the target ratio is the honest comparison).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — ALWAYS,
even on backend failure or timeout (an "error" field is added and the best
rate measured so far is reported, 0.0 if none).

Methodology: sign a small set of distinct messages (pure-Python RFC 8032),
tile to the bench batch, stage prepared arrays on device, then time
steady-state jitted verify passes with block_until_ready. Compiles are
ramped (a small batch is compiled and timed first) so a wedged device or a
pathological compile fails fast instead of hanging the driver. Host batch
prep is timed and reported separately in the JSON for honesty; the headline
is device throughput (host prep overlaps with device compute in the
pipelined runtime — see crypto/tpu_verifier.py).

Attach strategy (round 4): the tunnel to the chip flaps for hours at a
time, and a single blocking `jax.devices()` can hang forever — rounds 1-3
each burned their whole driver budget inside one attach attempt. So the
default entrypoint is now a small ORCHESTRATOR that never imports jax:
it probes the tunnel in a subprocess with a short timeout, retries in a
loop across the whole budget, and only once a probe confirms a live
non-CPU device does it spawn the real measurement as a `--_worker`
subprocess (which keeps its own watchdog). Any nonzero measurement the
worker produces — even one cut short by a later hang — is forwarded, so
a healthy window of any length turns into a recorded number.

Env knobs: BENCH_BATCH (top batch size; capped at 8192 unless
BENCH_ALLOW_BIG=1 — a killed 16384+ compile wedged the device tunnel for
hours once, so big compiles never run inside the default driver budget),
BENCH_SIGNERS, BENCH_TIMEOUT (wall-clock budget in seconds, default 420),
BENCH_PROBE_TIMEOUT (per-attach-probe subprocess timeout, default 45),
BENCH_PROBE_RETRY_SLEEP (pause between failed probes, default 20),
BENCH_DIRECT=1 (skip the orchestrator: attach + measure in-process,
for hosts with a known-good local device),
BENCH_MODE (fused|comb — fused is one gather + one mixed add per nibble
position, half the comb engine's madds), BENCH_WINDOW (fused window bits,
4|5|6), BENCH_MUL (skew|padacc field-multiply formulation), BENCH_ACCUM
(auto|xla|pallas madd-loop implementation; auto = pallas on real TPU),
BENCH_PALLAS_TILE (batch lanes per Pallas program), BENCH_RAMP
(fast|full; default fast = one small fail-fast compile then the top
batch — fastest path to a steady-state number under the driver budget;
full = the whole power-of-two ladder), BENCH_CACHE=0 (disable the
persistent jit cache), --smoke (tiny CPU run for CI). The JSON also
reports e2e_verifies_per_sec: the overlapped host-prep + transfer +
device rate.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_best = {"value": 0.0, "batch": 0, "note": "no measurement completed"}
# facts that must survive into a watchdog-truncated record (platform, mode,
# ...) — set as soon as known, merged into every emitted line
_sticky: dict = {}
# orchestrator only: the best worker record captured so far; the single
# emit path below prefers it over a zero/error line, so a measurement in
# hand always beats a timeout report no matter which thread emits
_best_rec: dict | None = None
_emit_lock = threading.Lock()
_emitted = False


def _emit(error: str | None = None, **extra) -> None:
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
        if _best_rec is not None and _best_rec.get("value", 0) >= _best["value"]:
            rec = dict(_best_rec)
            if error is not None:
                rec["orchestrator_error"] = error[:300]
        else:
            rec = {
                "metric": "ed25519_verifies_per_sec_per_chip",
                "value": round(_best["value"], 1),
                "unit": "verifies/s",
                "vs_baseline": round(_best["value"] / 1_000_000, 4),
                "batch": _best["batch"],
                "note": _best["note"],
            }
            rec.update(_sticky)
            if error is not None:
                rec["error"] = error[:500]
        rec.update(extra)
        # os.write on the raw fd: must succeed even if the main thread is
        # wedged inside a jaxlib C call holding buffered-stdout state.
        os.write(1, (json.dumps(rec) + "\n").encode())


def _start_watchdog(budget: float) -> None:
    """SIGALRM can't preempt a blocking jaxlib C call (compile /
    block_until_ready) — exactly the wedge scenarios this guard exists
    for. A daemon thread + os._exit actually fires."""

    def fire():
        time.sleep(max(1.0, budget))
        _emit(error=f"timeout after {budget:.0f}s: {_best['note']}")
        os._exit(0)

    threading.Thread(target=fire, daemon=True).start()


def _measure(fn, arrays, batch: int, min_s: float, max_iters: int) -> float:
    """Steady-state verifies/s for a compiled fn at this batch size."""
    out = fn(*arrays)
    out.block_until_ready()  # warm pass (post-compile)
    iters = 0
    t0 = time.perf_counter()
    while True:
        out = fn(*arrays)
        iters += 1
        if iters >= max_iters or (
            iters >= 3 and time.perf_counter() - t0 > min_s
        ):
            break
    out.block_until_ready()
    elapsed = time.perf_counter() - t0
    return batch * iters / elapsed


def _worker_main() -> None:
    budget = float(os.environ.get("BENCH_TIMEOUT", "420"))
    _start_watchdog(budget)
    t_start = time.perf_counter()

    # The note rides along in the timeout JSON — keep it pointing at the
    # exact stage so a wedged run says *where* it wedged (backend init is
    # the historical culprit: a remote-device tunnel can hang jax.devices()
    # indefinitely).
    _best["note"] = "initializing jax backend"
    import jax

    if os.environ.get("BENCH_CACHE", "1") != "0":
        # Persistent compile cache: a re-run after a timeout (or the
        # driver's run after an experiment) skips straight to measuring.
        from simple_pbft_tpu import enable_jit_cache

        enable_jit_cache()

    if "--smoke" in sys.argv:
        # CPU, tiny batch: CI-checkable in seconds. The ambient
        # sitecustomize force-registers the axon TPU backend (overriding
        # the JAX_PLATFORMS env var), so override in-process before any
        # backend initializes.
        jax.config.update("jax_platforms", "cpu")
        os.environ.setdefault("BENCH_BATCH", "64")

    import jax.numpy as jnp

    from simple_pbft_tpu.ops import field25519 as fe

    mul_impl = os.environ.get("BENCH_MUL", "padacc")
    fe.use_mul_impl(mul_impl)  # must precede any jit trace

    from simple_pbft_tpu.ops import comb

    accum_impl = os.environ.get("BENCH_ACCUM", "auto")
    comb.use_accum_impl(accum_impl)
    comb.PALLAS_TILE = int(os.environ.get("BENCH_PALLAS_TILE", comb.PALLAS_TILE))
    from simple_pbft_tpu.crypto import ed25519_cpu as ref
    from simple_pbft_tpu.crypto.verifier import BatchItem
    from simple_pbft_tpu.crypto.tpu_verifier import (
        BUCKETS,
        KeyBank,
        prepare_comb_batch,
        prepare_wire_batch,
    )

    mode = os.environ.get("BENCH_MODE", "fused")
    assert mode in ("fused", "comb"), mode
    # comb mode is fixed at 4-bit windows; report what actually runs.
    # Default window is 5: the round-4 on-chip A/B measured w4 610k /
    # w5 777k / skew 322k verifies/s (bench_results/chip_r04.jsonl), so
    # the driver's bare `python bench.py` run measures the best config.
    wbits = int(os.environ.get("BENCH_WINDOW", "5")) if mode == "fused" else 4
    # BENCH_ROWPACK=1: 15-bit limb pairs share an int32 in the table
    # rows (128-byte rows instead of 256), halving the madd gather's HBM
    # traffic for two shift/mask ops per element — fused mode only. The
    # switch must precede KeyBank construction and every jit trace.
    rowpack = mode == "fused" and os.environ.get("BENCH_ROWPACK", "0") == "1"
    comb.use_row_packing(rowpack)
    _sticky.update(mode=mode, window=wbits, mul=mul_impl, rowpack=rowpack)
    _best["note"] = "querying devices (tunnel attach)"
    platform = jax.devices()[0].platform
    _sticky["platform"] = platform
    _best["note"] = f"devices up ({platform}); preparing batch"
    top_batch = int(os.environ.get("BENCH_BATCH", str(BUCKETS[-1])))
    # comb kernel's batch inversion needs a power-of-two batch
    top_batch = 1 << max(0, top_batch - 1).bit_length()
    if top_batch > BUCKETS[-1] and os.environ.get("BENCH_ALLOW_BIG") != "1":
        print(
            f"capping batch {top_batch} -> {BUCKETS[-1]} "
            "(BENCH_ALLOW_BIG=1 to override)",
            file=sys.stderr,
        )
        top_batch = BUCKETS[-1]
    # committee-shaped workload: 16 signers (BASELINE config 2), distinct
    # messages per signer
    n_signers = int(os.environ.get("BENCH_SIGNERS", "16"))
    distinct = min(top_batch, 64)

    items = []
    for i in range(distinct):
        seed = bytes([i % n_signers]) * 32
        msg = b"bench vote %d" % i
        items.append(BatchItem(ref.public_key(seed), msg, ref.sign(seed, msg)))

    bank = KeyBank(mode=mode, window=wbits)
    _best["note"] = f"building {mode} key tables ({n_signers} keys)"
    t0 = time.perf_counter()
    for it in items:
        bank.lookup(it.pubkey)  # warm the bank: table build is one-time
    table_build_s = time.perf_counter() - t0

    # host prep cost, measured WARM at the top batch size (the per-item
    # number a pipelined replica actually pays; a cold 64-item batch
    # overstates it ~20x in fixed overheads)
    prepare = prepare_wire_batch if mode == "fused" else prepare_comb_batch
    items_top = items * (top_batch // distinct)
    prepare(items_top, bank)  # warm
    t0 = time.perf_counter()
    for _ in range(3):
        prep, _fallback = prepare(items_top, bank)
    prep_per_item_us = (time.perf_counter() - t0) / 3 / len(items_top) * 1e6

    prep, _fallback = prepare(items, bank)
    base_arrays = prep.arrays()
    tables = bank.device_tables()

    # The key tables are an ARGUMENT of the jitted fn, never a closure
    # capture: a closed-over array is embedded in the lowered program as a
    # constant, and XLA's constant handling scales with its bytes — the
    # fused bank is 67 MB at w=4 but 720 MB at w=6 (16 keys x 45 MB),
    # which pushed the w=6 compile past any sane budget. As a parameter
    # the table costs one transfer and zero compile time.
    if mode == "comb":
        b_table = comb.base_table_device()
        const_args = (tables, b_table)

        def fn(tables, b_table, s_nib, k_nib, a_idx, r_y, r_sign, precheck):
            return comb.comb_verify_kernel(
                s_nib, k_nib, a_idx, tables, b_table, r_y, r_sign, precheck
            )
    else:
        # fused staging is the WIRE path (raw (B, 96) uint8 on the link,
        # window/limb unpack fused into the kernel prologue) — the same
        # program TpuVerifier runs under consensus traffic
        const_args = (tables,)

        def fn(tables, wire, a_idx, precheck):
            return comb.fused_verify_wire_kernel(
                wire, a_idx, tables, precheck, window=1 << wbits
            )

    fn = jax.jit(fn)

    def effective(batch: int) -> int:
        return distinct * max(1, batch // distinct)

    # batch axis: trailing on comb's prepared arrays, LEADING on wire's
    stage_axis = 0 if mode == "fused" else -1

    def staged(batch: int):
        reps = batch // distinct
        return [
            *const_args,
            *(
                jax.device_put(np.concatenate([a] * reps, axis=stage_axis))
                for a in base_arrays
            ),
        ]

    # Ramp: compile small first so a wedged device / runaway compile fails
    # inside the watchdog window with a useful note, then step up through
    # power-of-two batches while time and measured rate justify it.
    # Default is the fast ramp — two compiles is the quickest route to a
    # steady-state number, and an environment hiccup mid-run then still
    # leaves a real measurement for the watchdog to report.
    ramp = os.environ.get("BENCH_RAMP", "fast")
    assert ramp in ("fast", "full"), ramp
    if ramp != "full":
        # one small fail-fast compile, then the top batch
        ladder = sorted({effective(min(64, top_batch)), effective(top_batch)})
    else:
        ladder = sorted(
            {
                effective(b)
                for b in (min(64, top_batch), top_batch, *BUCKETS)
                if b <= top_batch
            }
            | {effective(top_batch)}
        )
    compile_s = {}
    best_note = _best["note"]
    for batch in ladder:
        remaining = budget - (time.perf_counter() - t_start)
        # the first compile is the slow one; later ones re-tile the same
        # kernel. Leave margin: skip the step if under 25% of budget left.
        if remaining < 0.25 * budget and compile_s:
            best_note += f"; skipped batch>={batch} (time budget)"
            break
        arrays = staged(batch)
        _best["note"] = f"compiling batch={batch} on {platform}; best: {best_note}"
        t0 = time.perf_counter()
        verdict = np.asarray(fn(*arrays))
        compile_s[batch] = time.perf_counter() - t0
        assert verdict.all(), "bench batch must verify valid"
        _best["note"] = f"measuring batch={batch} on {platform}; best: {best_note}"
        rate = _measure(fn, arrays, batch, min_s=2.0, max_iters=30)
        if rate > _best["value"]:
            _best["value"] = rate
            _best["batch"] = batch
            best_note = f"batch={batch} on {platform}"
        _best["note"] = best_note
        print(
            f"batch={batch} rate={rate:,.0f}/s compile={compile_s[batch]:.1f}s",
            file=sys.stderr,
        )
    _best["note"] = best_note

    # Optional profiler capture (SURVEY.md §5: "JAX profiler traces for
    # the verify kernel"): BENCH_PROFILE=<dir> records a trace of a few
    # steady-state passes at the best batch, viewable in TensorBoard /
    # Perfetto. Guarded: profiling over the remote-device tunnel can be
    # unsupported, and a failed capture must not cost the bench run.
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir and _best["batch"]:
        try:
            arrays = staged(_best["batch"])
            with jax.profiler.trace(profile_dir):
                for _ in range(3):
                    out = fn(*arrays)
                out.block_until_ready()
            print(f"profiler trace written to {profile_dir}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"profiler capture failed: {e!r}", file=sys.stderr)

    # End-to-end: the full verify path per batch — host prep (wire bytes ->
    # arrays, native SHA-512 challenges), host->device transfer, kernel
    # dispatch. Dispatches are async, so the device verifies batch k while
    # the host preps batch k+1 — the overlap the pipelined runtime gets.
    e2e_rate = 0.0
    e2e_pipe_rate = 0.0
    if _best["batch"]:
        b_best = _best["batch"]
        items_big = items * (b_best // distinct)
        _best["note"] = f"e2e at batch={b_best}; best: {best_note}"

        def put_dispatch(arrays):
            return fn(*const_args, *(jax.device_put(a) for a in arrays))

        def e2e_loop(dispatch, finish) -> float:
            """One closed prepare->dispatch loop; `finish(last)` blocks
            on the final in-flight work. Shared by the serial and
            pipelined variants so the cutoff policy lives once."""
            last = None
            iters = 0
            t0 = time.perf_counter()
            while iters < 50 and (
                iters < 3 or time.perf_counter() - t0 < 3.0
            ):
                prep_i, _fb = prepare(items_big, bank)
                last = dispatch(prep_i.arrays(), last)
                iters += 1
            finish(last)
            return b_best * iters / (time.perf_counter() - t0)

        def remaining() -> float:
            return budget - (time.perf_counter() - t_start)

        # Guarded like the profiler capture above: an e2e failure (e.g. a
        # tunnel hiccup mid-transfer) must not discard the device-rate
        # measurement already in hand. Budget-checked so a slow-prep
        # config can't ride into the watchdog and lose the whole record.
        try:
            if remaining() > 0.10 * budget:
                e2e_rate = e2e_loop(
                    lambda arrays, _prev: put_dispatch(arrays),
                    lambda last: last.block_until_ready(),
                )
            # Pipelined: host prep of batch k+1 overlaps transfer +
            # device pass of batch k (a worker thread owns put+dispatch;
            # JAX dispatch is thread-safe) — the overlap the replica
            # runtime's two-worker verify pipeline gets for free.
            if remaining() > 0.10 * budget:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(1) as pool:

                    def disp(arrays, prev):
                        if prev is not None:
                            prev.result()  # keep queue depth at 1
                        return pool.submit(put_dispatch, arrays)

                    e2e_pipe_rate = e2e_loop(
                        disp,
                        lambda last: last.result().block_until_ready(),
                    )
        except Exception as e:  # noqa: BLE001
            print(f"e2e measurement failed: {e!r}", file=sys.stderr)
        _best["note"] = best_note

    print(
        f"host_prep={prep_per_item_us:.1f}us/item "
        f"table_build={table_build_s:.1f}s device={platform} "
        f"best={_best['value']:,.0f}/s e2e={e2e_rate:,.0f}/s ({_best['note']})",
        file=sys.stderr,
    )
    _emit(
        host_prep_us_per_item=round(prep_per_item_us, 2),
        # null = not measured (budget skip / failure) — a literal 0.0
        # would read as a catastrophic regression in the jsonl record
        e2e_verifies_per_sec=round(e2e_rate, 1) if e2e_rate else None,
        e2e_pipelined_verifies_per_sec=(
            round(e2e_pipe_rate, 1) if e2e_pipe_rate else None
        ),
        table_build_s=round(table_build_s, 1),
        staging="wire" if mode == "fused" else "prep",
        platform=platform,
        mode=mode,
        window=wbits,
        mul=mul_impl,
        # what actually ran, not "auto"; comb mode has no Pallas path
        accum=comb._resolve_accum_impl() if mode == "fused" else "xla",
    )


# --- orchestrator (no jax imports in this section) -----------------------

DAEMON_PORT = int(os.environ.get("CHIP_DAEMON_PORT", "48765"))


def _daemon_request(req: dict, timeout: float) -> dict | None:
    """One JSON-line round trip to the chip daemon (tools/chip_daemon.py).
    None = no daemon listening / bad reply — the caller falls back to
    probing the tunnel itself."""
    import socket

    try:
        with socket.create_connection(("127.0.0.1", DAEMON_PORT), timeout=5.0) as s:
            s.settimeout(timeout)
            s.sendall((json.dumps(req) + "\n").encode())
            buf = b""
            while b"\n" not in buf and len(buf) < 1 << 20:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        return json.loads(buf.split(b"\n", 1)[0].decode())
    except (OSError, ValueError):
        return None


def _try_daemon(deadline: float) -> dict | None:
    """Ask the persistent chip daemon for a LIVE measurement (VERDICT r4
    next #3: the device tunnel is effectively single-tenant, so while
    the watcher family holds it, this process's own attach would hang —
    four rounds of driver-slot probes died exactly that way). Polls
    until the daemon frees the device or the budget is nearly spent.
    Returns the measurement record, or None to fall back to probing."""
    first = _daemon_request({"cmd": "status"}, timeout=15.0)
    if first is None:
        print("no chip daemon listening; falling back to probes", file=sys.stderr)
        return None
    print(f"chip daemon status: {first}", file=sys.stderr)
    attempt = 0
    while True:
        remaining = deadline - time.time()
        if remaining < 90:
            return None
        attempt += 1
        _best["note"] = f"asking chip daemon (attempt {attempt})"
        # wait_s bounds how long the daemon holds our request while an
        # experiment owns the device; keep polls short enough to retry
        rec = _daemon_request(
            {"cmd": "measure", "min_s": 2.0, "wait_s": min(60.0, remaining - 75)},
            timeout=min(300.0, remaining - 60),
        )
        if rec is None:
            return None
        if (
            rec.get("ok")
            and rec.get("value", 0) > 0
            and rec.get("platform") not in (None, "cpu")
        ):
            rec["source"] = "chip_daemon"
            return rec
        why = rec.get("why") or ("busy: " + str(rec.get("current_exp")))
        print(f"daemon measure attempt {attempt}: {why}", file=sys.stderr)
        time.sleep(min(20.0, max(0.0, deadline - time.time() - 90)))

_PROBE_SRC = r"""
import json, time
t0 = time.time()
import jax
d = jax.devices()[0]
jax.device_put(1.0, d)
print(json.dumps({"platform": d.platform, "attach_s": round(time.time() - t0, 1)}))
"""


def _last_json_line(text: str) -> dict | None:
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _probe(timeout_s: float) -> dict:
    """Attach to the device in a THROWAWAY subprocess. A hung attach
    (the historical failure mode: tunnel up enough to register the
    backend, dead enough that jax.devices() never returns) costs
    `timeout_s`, not the whole budget. The subprocess is killed while
    still attaching — before any compile — which experience says the
    tunnel tolerates (unlike mid-compile kills)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "why": f"attach hung >{timeout_s:.0f}s"}
    info = _last_json_line(r.stdout)
    if info is not None:
        if info.get("platform") == "cpu":
            # attach "succeeded" but no chip is visible (axon backend
            # absent/declined) — for the chip metric that is a failure;
            # CPU-host users run --smoke or BENCH_DIRECT=1 instead
            return {"ok": False, "why": "attach ok but only cpu visible", **info}
        return {"ok": True, **info}
    tail = (r.stderr or "").strip().splitlines()
    return {
        "ok": False,
        "why": f"probe rc={r.returncode}: {tail[-1][:200] if tail else 'no output'}",
    }


def _run_worker(wbudget: float) -> dict | None:
    """Run the measurement in a subprocess with its own watchdog; return
    its JSON record (which the worker emits even on timeout)."""
    import subprocess

    env = dict(os.environ, BENCH_TIMEOUT=f"{wbudget:.0f}")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_worker"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=sys.stderr,
            text=True,
            timeout=wbudget + 30,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"worker hard-hung past its {wbudget:.0f}s watchdog"}
    return _last_json_line(r.stdout) or {
        "error": f"worker rc={r.returncode} emitted no JSON"
    }


def main() -> None:
    global _best_rec
    budget = float(os.environ.get("BENCH_TIMEOUT", "420"))
    _start_watchdog(budget)
    deadline = time.time() + budget
    probe_t = float(os.environ.get("BENCH_PROBE_TIMEOUT", "45"))
    retry_sleep = float(os.environ.get("BENCH_PROBE_RETRY_SLEEP", "20"))
    probes: list[dict] = []
    last_worker_err = None
    # 1) daemon-first: a live measurement through the persistent worker
    #    costs seconds and never competes for the single-tenant tunnel.
    #    BOUNDED at ~45% of the budget: a listening-but-useless daemon
    #    (device held by an experiment all round) used to absorb the
    #    whole window, leaving the legacy path ONE probe attempt (round
    #    5) where the probe-loop design wants six (round 4) — the probes
    #    must own the majority of the budget.
    rec = _try_daemon(min(deadline, time.time() + 0.45 * budget))
    if rec is not None:
        rec = {
            "metric": "ed25519_verifies_per_sec_per_chip",
            "value": round(rec["value"], 1),
            "unit": "verifies/s",
            "vs_baseline": round(rec["value"] / 1_000_000, 4),
            **{
                k: rec[k]
                for k in (
                    "batch", "window", "mode", "platform", "measured_at",
                    "live", "source", "compile_s", "attach_s",
                )
                if k in rec
            },
        }
        _best_rec = rec
        _emit()
        return
    # 2) legacy path: probe + attach ourselves
    while True:
        remaining = deadline - time.time()
        if remaining < 75:
            break
        _best["note"] = f"probing tunnel (attempt {len(probes) + 1})"
        res = _probe(min(probe_t, remaining - 30))
        probes.append(res)
        print(f"probe {len(probes)}: {res}", file=sys.stderr)
        if res.get("ok"):
            # leave margin so the worker's own watchdog emission, the
            # subprocess timeout (+30) and our forwarding all land before
            # the orchestrator watchdog fires at `budget`
            wbudget = deadline - time.time() - 45
            if wbudget < 50:
                break
            _best["note"] = f"worker measuring (probe ok, attach {res.get('attach_s')}s)"
            rec = _run_worker(wbudget)
            if rec and rec.get("value", 0) > 0:
                rec["probe_attempts"] = len(probes)
                rec["attach_s"] = res.get("attach_s")
                # a real measurement (possibly truncated): hold it where
                # every emit path — clean exit, watchdog, exception —
                # prefers it over a zero/error line
                if _best_rec is None or rec["value"] > _best_rec.get("value", 0):
                    _best_rec = rec
                if "error" not in rec:
                    _emit()
                    return
            last_worker_err = (rec or {}).get("error", "worker emitted nothing")
            print(f"worker attempt failed: {last_worker_err}", file=sys.stderr)
        else:
            time.sleep(max(0.0, min(retry_sleep, deadline - time.time() - 75)))
    if _best_rec is not None:
        _emit()
        return
    last = probes[-1] if probes else {"why": "no probe ran"}
    err = (
        f"no chip measurement in {budget:.0f}s after {len(probes)} probe "
        f"attempts; last probe: {last.get('why', last)}"
    )
    if last_worker_err:
        err += f"; last worker error: {last_worker_err}"
    _emit(
        error=err,
        probe_attempts=len(probes),
        prior_recorded=_best_prior_record(),
    )


def _best_prior_record() -> dict | None:
    """Prior chip measurement from the repo's recorded evidence
    (bench_results/chip_r*.jsonl — possibly from an EARLIER round; the
    `source`/`ts` fields say which). Preference order: the FRESHEST line
    matching the CURRENT config (BENCH_MODE/BENCH_WINDOW) — that is the
    number this run would have reproduced — falling back to the global
    best by value when no same-config line exists. Decoration for the
    total-failure error line only, never the live value: when the tunnel
    is dead for the driver's whole budget (rounds 1-3 lost every window
    this way), the report at least points at the real, separately-
    recorded evidence instead of a bare 0.0. Best-effort by contract:
    ANY failure returns None — this helper runs inside the error-emit
    path and must never be the reason no JSON line appears."""
    try:
        import glob

        mode = os.environ.get("BENCH_MODE", "fused")
        wbits = int(os.environ.get("BENCH_WINDOW", "5")) if mode == "fused" else 4
        results_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_results"
        )
        best = None
        same_cfg = None  # freshest (by ts) line matching mode/window
        for path in sorted(
            glob.glob(os.path.join(results_dir, "chip_r*.jsonl"))
        ):
            with open(path) as f:
                for line in f:
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue
                    rec = d.get("rec") or {}
                    value = rec.get("value")
                    if not (
                        d.get("ok")
                        and isinstance(value, (int, float))
                        and value > 0
                    ):
                        continue
                    entry = {
                        "value": value,
                        "exp": d.get("exp"),
                        "ts": d.get("ts"),
                        "source": os.path.relpath(
                            path, os.path.dirname(results_dir)
                        ),
                    }
                    if best is None or value > best["value"]:
                        best = entry
                    if rec.get("mode") == mode and rec.get("window") == wbits:
                        # ISO timestamps: lexicographic max = freshest; a
                        # ts-less line sorts lowest (compares as "") so it
                        # can never shadow genuinely dated evidence
                        if same_cfg is None or str(d.get("ts") or "") >= str(
                            same_cfg.get("ts") or ""
                        ):
                            same_cfg = dict(entry, same_config=True)
        return same_cfg or best
    except Exception:  # noqa: BLE001 — see docstring
        return None


if __name__ == "__main__":
    try:
        if "--_worker" in sys.argv or "--smoke" in sys.argv or (
            os.environ.get("BENCH_DIRECT") == "1"
        ):
            _worker_main()
        else:
            main()
    except BaseException as e:  # noqa: BLE001 — always emit the JSON line
        if not isinstance(e, SystemExit):
            _emit(error=f"{type(e).__name__}: {e}")
            raise
        raise
