"""Benchmark: batched Ed25519 verification throughput on the attached chip.

Headline metric (BASELINE.md): Ed25519 verifies/sec on one chip; target is
>= 1,000,000/s (`vs_baseline` is value / 1e6 — the reference itself verifies
zero signatures, SURVEY.md §6, so the target ratio is the honest comparison).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology: sign a small set of distinct messages (pure-Python RFC 8032),
tile to the bench batch, stage prepared arrays on device, then time
steady-state jitted verify passes with block_until_ready. Host batch prep
is excluded from the headline (it overlaps with device compute in the
pipelined runtime) but reported on stderr for honesty.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    if "--smoke" in sys.argv:
        # CPU, tiny batch: CI-checkable in seconds. The ambient
        # sitecustomize force-registers the axon TPU backend (overriding
        # the JAX_PLATFORMS env var), so override in-process before any
        # backend initializes.
        jax.config.update("jax_platforms", "cpu")
        os.environ.setdefault("BENCH_BATCH", "8")

    import jax.numpy as jnp

    from simple_pbft_tpu.ops import comb
    from simple_pbft_tpu.crypto import ed25519_cpu as ref
    from simple_pbft_tpu.crypto.verifier import BatchItem
    from simple_pbft_tpu.crypto.tpu_verifier import (
        BUCKETS,
        KeyBank,
        prepare_comb_batch,
    )

    batch = int(os.environ.get("BENCH_BATCH", str(BUCKETS[-1])))
    # comb kernel's batch inversion needs a power-of-two batch
    batch = 1 << max(0, batch - 1).bit_length()
    # committee-shaped workload: 16 signers (BASELINE config 2), distinct
    # messages per signer
    n_signers = int(os.environ.get("BENCH_SIGNERS", "16"))
    distinct = min(batch, 64)

    items = []
    for i in range(distinct):
        seed = bytes([i % n_signers]) * 32
        msg = b"bench vote %d" % i
        items.append(BatchItem(ref.public_key(seed), msg, ref.sign(seed, msg)))

    bank = KeyBank()
    t0 = time.perf_counter()
    prep, _fallback = prepare_comb_batch(items, bank)
    prep_per_item = (time.perf_counter() - t0) / distinct

    reps = max(1, batch // distinct)
    batch = distinct * reps  # keep the rate honest when batch % distinct != 0
    arrays = [
        jax.device_put(np.concatenate([a] * reps, axis=0)) for a in prep.arrays()
    ]
    tables = bank.device_tables()
    b_table = jnp.asarray(comb.base_table())

    def fn(s_nib, k_nib, a_idx, r_y, r_sign, precheck):
        return comb.comb_verify_kernel(
            s_nib, k_nib, a_idx, tables, b_table, r_y, r_sign, precheck
        )

    fn = jax.jit(fn)
    t0 = time.perf_counter()
    verdict = np.asarray(fn(*arrays))
    compile_s = time.perf_counter() - t0
    assert verdict.all(), "bench batch must verify valid"

    # steady state: run until >= 3 s of device time or 30 iters
    iters = 0
    t0 = time.perf_counter()
    while True:
        out = fn(*arrays)
        iters += 1
        if iters >= 30 or (iters >= 3 and time.perf_counter() - t0 > 3.0):
            break
    out.block_until_ready()
    elapsed = time.perf_counter() - t0

    value = batch * iters / elapsed
    print(
        f"batch={batch} iters={iters} elapsed={elapsed:.3f}s "
        f"compile={compile_s:.1f}s host_prep={prep_per_item*1e6:.1f}us/item "
        f"device={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "verifies/s",
                "vs_baseline": round(value / 1_000_000, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
