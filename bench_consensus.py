"""Consensus-throughput benchmark: committed requests/s on a LocalCommittee.

BASELINE.md config ladder, measured end to end through the real stack
(signed wire messages, batch verification, ordered execution, replies):

  1. n=4  (f=1), CPU verify        — parity with the reference's run.bat
  2. n=16 (f=5), TPU batched verify (--verifier tpu)
  3. n=64, many concurrent clients, QC batching
  4. n=256, BLS aggregate quorum certificates (qc_mode: one pairing
     check per QC instead of 2f+1 signature checks; crypto/bls.py)
  5. n=64 view-change storm (--storm): crash the primary mid-load,
     measure failover + post-failover throughput.

The load is throughput-bound: `--outstanding` concurrent in-flight
requests are kept open per client (closed-loop with high concurrency),
so the committee pipelines many sequence numbers (the reference was
hard-serialized at one in-flight instance ≈ 0.3-0.5 req/s; SURVEY.md §6).

Prints ONE JSON line per config:
  {"config", "n", "committed_req_s", "p50_ms", "p99_ms", ...}

Usage:
  python bench_consensus.py [--configs 1,2,3] [--verifier cpu|tpu]
      [--seconds 10] [--clients 8] [--outstanding 64] [--storm]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time
from typing import List

# plain hosts honor the env var; chip-tunnel hosts override it via
# sitecustomize (axon), which is exactly right for --verifier tpu runs
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
if os.environ.get("BENCH_FORCE_CPU") == "1":
    # shake out --verifier tpu plumbing without the chip: must run
    # BEFORE any simple_pbft_tpu import could touch a jax backend
    from simple_pbft_tpu import force_cpu

    force_cpu()


def _emit(rec: dict) -> None:
    os.write(1, (json.dumps(rec) + "\n").encode())


def _start_watchdog(budget: float) -> None:
    """Hard wall-clock bound: dump every thread's stack to stderr and
    exit. A wedged scenario (e.g. a certificate-validation pile-up) must
    produce a diagnosable artifact, not an eternal process."""
    import faulthandler
    import threading
    import time as _t

    def fire():
        _t.sleep(budget)
        print(f"WATCHDOG: wall clock exceeded {budget:.0f}s", file=sys.stderr)
        faulthandler.dump_traceback(file=sys.stderr)
        _emit({"config": "watchdog-timeout", "budget_s": budget})
        os._exit(3)

    threading.Thread(target=fire, daemon=True).start()


from simple_pbft_tpu.client import SupersededError


def _committee_telemetry(com, service=None) -> dict:
    """Committee-wide aggregate of the unified telemetry plane
    (simple_pbft_tpu/telemetry.py): replica counters summed, transport
    counters summed, execution frontier spread, verify-service snapshot.
    Scraped at the start and end of the measurement window so every
    BENCH_*.json cell carries the telemetry that explains it."""
    from collections import defaultdict

    from simple_pbft_tpu.telemetry import SCHEMA_VERSION, wire_aggregate
    from simple_pbft_tpu.transport.base import wire_of

    agg, tx = defaultdict(int), defaultdict(int)
    wires = []
    for r in com.replicas:
        for k, v in r.metrics.items():
            agg[k] += v
        for k, v in getattr(r.transport, "metrics", {}).items():
            tx[k] += v
        w = wire_of(r.transport)
        if w is not None:
            wires.append(w.per_kind())
    exec_seqs = sorted(r.executed_seq for r in com.replicas)
    out = {
        "schema": SCHEMA_VERSION,
        "t_wall": round(time.time(), 3),
        "replicas_running": sum(1 for r in com.replicas if r._running),
        "exec_seq_min": exec_seqs[0] if exec_seqs else 0,
        "exec_seq_max": exec_seqs[-1] if exec_seqs else 0,
        "views": sorted({r.view for r in com.replicas}),
        "replica_metrics": dict(sorted(agg.items())),
        "transport": dict(sorted(tx.items())),
        # committee-wide per-kind msgs+bytes (ISSUE 12 wire accounting):
        # scraped at window start AND end so the record's wire block is a
        # pure measurement-window delta
        "wire_per_kind": wire_aggregate(wires),
    }
    if service is not None:
        out["verify"] = service.snapshot()
    return out


async def _pump(client, stop_at: float, latencies: List[float], errors: List[int]):
    """One closed-loop driver: keep exactly one request in flight, record
    per-request latency. Concurrency comes from running many of these.
    Retries are sized so total client patience (~(retries+1) x timeout)
    exceeds any plausible failover stall — a request abandoned by the
    pump vanishes from the latency distribution, silently flattering
    p99 exactly when the system was slowest."""
    i = 0
    # Patience must exceed the worst-case failover-plus-congestion
    # recovery or the sample is censored exactly when the system is
    # slowest: measured at n=64/QC on this one-core host, a view change
    # under chaos can take ~45 s to drain its queue backlog, and a
    # request committed at t+45 whose replies are still in flight is a
    # tail latency sample, not a timeout.
    # retry COUNT derived from the patience budget under the client's
    # backoff schedule (client.retries_for_patience): a fixed count
    # would mean minutes of tail patience now that retries back off
    retries = max(3, client.retries_for_patience(75.0))
    while time.perf_counter() < stop_at:
        t0 = time.perf_counter()
        try:
            await client.submit(
                f"put k{id(client) % 997}_{i % 64} {i}", retries=retries
            )
            # (completion time, latency): throughput is counted over the
            # measurement window only — a straggler finishing during the
            # drain tail must not deflate req/s by stretching `elapsed`
            latencies.append((time.perf_counter(), time.perf_counter() - t0))
        except (asyncio.TimeoutError, TimeoutError):
            errors.append(1)
        except SupersededError:
            # reply cache folded under a long storm before the client saw
            # f+1 matches: an explicit NACK, not a latency sample
            errors.append(1)
        i += 1


async def run_config(
    name: str,
    n: int,
    seconds: float,
    n_clients: int,
    outstanding: int,
    verifier: str,
    batch: int,
    storm: bool = False,
    qc_mode: bool = False,
    view_timeout: float = 0.0,
    chaos: dict = None,
    max_crashes: int = 3,
    fault_spec: str = None,
    verify_deadline: float = 60.0,
    verify_max_pending: int = 65536,
    status_port_base: int = 0,
    flight_dir: str = None,
    trace_sample: float = 0,
    stall_deadline: float = 30.0,
    device_profile: float = 0.0,
    speculative: bool = True,
) -> dict:
    from simple_pbft_tpu.committee import LocalCommittee
    from simple_pbft_tpu.crypto.coalesce import VerifyService
    from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier
    from simple_pbft_tpu.faults import (
        FaultInjector,
        FaultSchedule,
        SlowVerifier,
        StallableDevice,
    )
    from simple_pbft_tpu.transport.local import FaultPlan

    # deterministic fault schedule (simple_pbft_tpu/faults.py): the
    # chaos-on-TPU cell and the crash-count-matched storm A/B both key
    # off --fault-schedule so a run's faults are a pure function of its
    # seed — reproducible, host-independent, diffable between A/B arms
    schedule = None
    if isinstance(fault_spec, FaultSchedule):
        # --replay: the EXACT recorded schedule (rebuilt from a ledger
        # line's faults block via FaultSchedule.from_summary), never a
        # re-parse — replay must not depend on generate()'s dealing
        schedule = fault_spec
    elif fault_spec:
        schedule = FaultSchedule.parse(
            fault_spec, horizon=seconds,
            replica_ids=[f"r{i}" for i in range(n)],
        )

    factory = None
    slow_wrap = None
    n_keys = n + n_clients + 8  # committee + clients + headroom
    if verifier == "insecure":
        from simple_pbft_tpu.crypto.verifier import InsecureVerifier

        factory = InsecureVerifier
    if (
        schedule
        and verifier in ("cpu", "insecure")
        and any(e.kind == "slow_verifier" for e in schedule.events)
    ):
        from simple_pbft_tpu.crypto.verifier import (
            InsecureVerifier,
            best_cpu_verifier,
        )

        # one shared slow-armable wrapper so the injector has a single
        # seam; sharing a CPU verifier across replicas is safe (stateless
        # beyond the process-wide row cache, which is already shared)
        slow_wrap = SlowVerifier(
            InsecureVerifier() if verifier == "insecure"
            else best_cpu_verifier()
        )
        factory = lambda: slow_wrap  # noqa: E731
    if verifier == "tpu":
        import simple_pbft_tpu

        simple_pbft_tpu.enable_jit_cache()
        # initial_keys pins every replica's key-table SHAPE to the final
        # key population: the jit signature includes that shape, so a
        # bank growing under live traffic means fresh 40-150 s compiles
        # serialized under the device lock mid-benchmark (measured: an
        # n=16 run burning its whole 120 s client patience compiling,
        # zero commits). Size once; warm at that exact shape below.
        #
        # ONE verifier shared by every replica: the committee shares one
        # key population, and per-replica banks would upload n copies of
        # the same table to one chip (n=64 at cap 128 is ~537 MB per
        # bank — 34 GB across replicas, over any single chip's HBM).
        # TpuVerifier is thread-safe (bank lock + device lock), exactly
        # for this shape of sharing. The VerifyService in front of it is
        # the round-5 architecture fix: every replica's sweep submits a
        # future and the service folds all pending work into ONE async
        # device pass (double-buffered), with a CPU path for tiny piles
        # — n sequential tunnel RTTs per round becomes ~1
        # (crypto/coalesce.py; VERDICT r4 next #1).
        shared_verifier = TpuVerifier(initial_keys=n_keys)
        device = shared_verifier
        if schedule is not None:
            # stall-injectable device front (faults.StallableDevice):
            # dispatches stay fast, finishers block while stalled — the
            # exact silent-tunnel shape the service watchdog guards
            device = StallableDevice(shared_verifier)
        # overload resilience (ISSUE 1): bounded admission + the
        # dispatch-deadline watchdog with CPU failover + quarantine.
        # --verify-deadline 0 disables the watchdog (pre-ISSUE-1 shape).
        service = VerifyService(
            device,
            max_pending=verify_max_pending,
            dispatch_deadline=verify_deadline if verify_deadline > 0 else None,
        )
        factory = lambda: service  # noqa: E731

    plan = None
    if chaos:
        plan = FaultPlan(
            drop_rate=chaos["drop"],
            delay_range=(0.0, chaos["delay"]),
            duplicate_rate=chaos["dup"],
            seed=chaos["seed"],
        )
    # Degraded-mode (storm/chaos) failover timer: 3 s is right when
    # verify is a local CPU call, but a tunneled device's sweep latency
    # is itself seconds — a 3 s timer then fires before ANY round can
    # finish and the committee view-changes perpetually from t=0
    # (measured: storm-on-chip with verify_calls=0 — not one drain sweep
    # completed). Scale the timer to the verify backend; co-located TPU
    # deployments (ms dispatches) can pass --view-timeout to tighten it.
    degraded_vt = 3.0 if verifier in ("cpu", "insecure") else 15.0
    com = LocalCommittee.build(
        n=n,
        clients=n_clients,
        fault_plan=plan,
        verifier_factory=factory,
        max_batch=batch,
        view_timeout=view_timeout
        or (30.0 if not (storm or chaos or schedule) else degraded_vt),
        checkpoint_interval=64,
        watermark_window=1024,
        qc_mode=qc_mode,
        # ISSUE 15: speculative execution at PREPARED (on by default;
        # --no-spec is the A/B arm measuring the pre-speculation shape)
        speculative=speculative,
    )
    for c in com.clients:
        # Storms/chaos: the first send of a request can go to a crashed
        # primary (storm) or get dropped outright (chaos) and NOTHING
        # reaches the committee until this timer triggers the broadcast
        # retry — so it must be a small multiple of failover time, not a
        # lazy 30 s (which was the entire tail of every storm p99).
        # Clean steady-state benches keep the long timeout so retries
        # never distort throughput numbers.
        degraded = storm or bool(chaos) or schedule is not None
        c.request_timeout = (
            1.5 * (view_timeout or degraded_vt) if degraded else 30.0
        )
        if degraded:
            # hedged first sends: a crashed primary or a dropped frame
            # must not leave the request unknown to the whole committee
            # (see client.Client.hedge)
            c.hedge = 2

    if verifier == "tpu":
        # Pre-pay every (bucket, table-shape) compile BEFORE the timed
        # window, with the committee's REAL key population so the warmed
        # shapes are the ones live sweeps hit. _shared_jit makes the
        # compiles process-wide, so one warmer covers all n replicas.
        # The warm budget must cover the COALESCED maximum, not one
        # replica's sweep: the service folds every replica's pending
        # items into one pile, so the first busy moment hits the top
        # bucket — an unwarmed bucket is a minutes-long compile at
        # dispatch, stalling the whole committee (caught by the r5
        # forced-CPU preflight: svc_max_coalesced=1917 wedged in the
        # 2048-bucket compile, zero commits).
        from simple_pbft_tpu.crypto.tpu_verifier import BUCKETS

        # Warm EVERY bucket: a per-round arithmetic bound is unsound —
        # while a multi-second device pass is in flight, each replica's
        # transport backlog accumulates several rounds (multiple
        # pre-prepares x batch client sigs per sweep, max_drain=4096
        # messages), and the service coalesces all replicas' sweeps, so
        # any bucket up to the service max is reachable under load. An
        # unwarmed bucket is a minutes-long compile under the device
        # lock mid-window; warm time is paid once, off the clock.
        need = BUCKETS[-1]
        t0 = time.perf_counter()
        shared_verifier.warm_for_population(
            [kp.pub for kp in com.keys.values()], max_sweep=need
        )
        print(
            f"warmed sweeps <= {need} at table cap "
            f"{shared_verifier._bank._cap} "
            f"in {time.perf_counter() - t0:.0f}s",
            file=sys.stderr,
        )
        # occupancy counters start at the timed window, not the warmup
        shared_verifier.device_calls = 0
        shared_verifier.device_items = 0
        shared_verifier.device_seconds = 0.0

    com.start()

    # live telemetry plane (ISSUE 2): per-replica /metrics.json endpoints
    # mid-run, crash-surviving flight-recorder timelines, and sampled
    # phase-level traces that join client and replica events. ISSUE 4
    # adds per-stage span attribution (spans.jsonl -> tools/
    # critical_path.py), the event-loop lag gauge, and per-replica
    # stall-autopsy watchdogs.
    from simple_pbft_tpu import spans as spans_mod
    from simple_pbft_tpu.telemetry import resolve_sample_mod

    status_servers = []
    recorders = []
    watchdogs = []
    tracers = {}
    lag_gauge = com.attach_loop_lag()
    # per-config span surface: configure() RESETS the process recorder,
    # so each ladder cell's rec["spans"] describes that cell alone, and
    # each cell gets its own <config>.spans.jsonl (critical_path
    # discovers *.spans.jsonl) instead of an append-mode mixture
    spans_mod.configure(
        name,
        os.path.join(flight_dir, f"{name}.spans.jsonl")
        if flight_dir else None,
    )
    # device-plane observatory (ISSUE 14): reset the per-dispatch device
    # ledger in lockstep with spans — after warm, per cell — so each
    # cell's rec["device"] aggregates describe that cell's window alone
    # and tools/verify_observatory.py can reconcile ledger vs spans
    from simple_pbft_tpu import devledger as devledger_mod

    devledger_mod.configure(name)
    if device_profile > 0 and flight_dir:
        devledger_mod.arm_profile(
            os.path.join(flight_dir, "device_profile"), device_profile
        )
    sample_mod = resolve_sample_mod(trace_sample)
    if sample_mod > 0:
        tracers = com.attach_tracers(
            sample_mod=sample_mod, trace_dir=flight_dir
        )
    # consensus audit plane (ISSUE 5): with a flight dir every replica
    # gets a SafetyAuditor — evidence + observation ledgers land next to
    # the flight timelines, so tools/ledger_audit.py can join the whole
    # committee's run post-hoc (and a --fault-schedule equiv=/forkckpt=
    # run proves detection end to end)
    auditors = {}
    if flight_dir:
        auditors = com.attach_auditors(log_dir=flight_dir)
    if status_port_base > 0 or flight_dir:
        from simple_pbft_tpu.telemetry import (
            FlightRecorder,
            ProgressWatchdog,
            StatusServer,
        )

        for i, r in enumerate(com.replicas):
            tel = com.node_telemetry(r.id)
            rec_f = None
            if status_port_base > 0:
                srv = StatusServer(tel, port=status_port_base + i)
                await srv.start()
                status_servers.append(srv)
            if flight_dir:
                rec_f = FlightRecorder(
                    tel,
                    os.path.join(flight_dir, f"{r.id}.flight.jsonl"),
                    interval=0.5,
                )
                rec_f.start()
                recorders.append(rec_f)
            if flight_dir and stall_deadline > 0 and not watchdogs:
                # wedge autopsy (ISSUE 4): a qc256-style silent stall in
                # a BENCH run now leaves <flight-dir>/<id>.autopsy.json
                # naming the stalled stage instead of a blank record.
                # ONE watchdog (the first replica), not n: in-process the
                # verify service, QC lane, task/thread stacks, and spans
                # are all process-wide, so a committee-wide stall would
                # trip every watchdog in the same poll interval and
                # serialize n near-identical full stack dumps on the
                # already-wedged loop (n=256: seconds of self-inflicted
                # freeze). One dump describes the committee; per-process
                # node.py deployments still get one per node.
                wd = ProgressWatchdog(
                    tel,
                    path=os.path.join(flight_dir, f"{r.id}.autopsy.json"),
                    deadline=stall_deadline,
                    flight=rec_f,
                )
                wd.start()
                watchdogs.append(wd)
                for aud in auditors.values():
                    # a safety violation fires the same forensic dump
                    # path as a stall (one autopsy per auditor)
                    aud.attach_watchdog(wd)
        if status_servers:
            print(
                f"telemetry: /metrics.json on 127.0.0.1:"
                f"{status_port_base}..{status_port_base + n - 1}",
                file=sys.stderr,
            )

    telemetry_start = _committee_telemetry(
        com, service if verifier == "tpu" else None
    )

    latencies: List[float] = []
    errors: List[int] = []
    t_start = time.perf_counter()
    stop_at = t_start + seconds
    per_client = max(1, outstanding // n_clients)
    pumps = [
        asyncio.create_task(_pump(c, stop_at, latencies, errors))
        for c in com.clients
        for _ in range(per_client)
    ]

    injector = None
    injector_task = None
    if schedule is not None:
        injector = FaultInjector(
            committee=com,
            schedule=schedule,
            service=service if verifier == "tpu" else None,
            slow=slow_wrap,
        )
        # the injector's deadline rides the CLOCK SEAM's timebase
        # (clock.now() — virtual under simulation), which shares no
        # epoch with the perf_counter-based bench window above
        from simple_pbft_tpu import clock as pbft_clock

        injector_task = asyncio.create_task(
            injector.run(pbft_clock.now() + seconds)
        )

    crash_info = {}
    if storm:
        # config 5: kill the primary mid-load REPEATEDLY; committee must
        # view-change and keep committing under each successor
        crashes = 0
        next_crash = t_start + seconds / 6
        while time.perf_counter() < stop_at - 1.0:
            await asyncio.sleep(0.2)
            if time.perf_counter() >= next_crash and crashes < max_crashes:
                view = max(r.view for r in com.replicas if r._running)
                target = com.replica(com.cfg.primary(view))
                if not target._running:
                    continue  # failover still in progress; don't double-count
                target.kill()  # crash-stop, no drain
                crashes += 1
                next_crash += seconds / 5
        crash_info = {"primary_crashes": crashes}

    await asyncio.gather(*pumps, return_exceptions=True)
    if injector_task is not None:
        injector.stop()  # cancel pending window restores (they restore)
        await asyncio.gather(injector_task, return_exceptions=True)
    elapsed = time.perf_counter() - t_start
    # throughput over the window; stragglers completing in the drain
    # tail still contribute their LATENCY samples below, honestly
    # fattening the percentiles instead of silently deflating req/s
    committed = sum(1 for done_at, _ in latencies if done_at <= stop_at)
    window = min(elapsed, seconds)
    # replica-side truth: total requests the (surviving) replicas executed
    exec_counts = sorted(
        r.metrics.get("committed_requests", 0) for r in com.replicas if r._running
    )
    # designated-replier fan-out: replies transmitted per committed
    # request committee-wide (cfg.repliers = f+1 plus loss spares;
    # everything beyond f+1 is deliberate redundancy, everything under
    # n is the rotation's savings vs reply-from-everyone)
    # (surviving replicas only, matching exec_counts — a crashed
    # replica's pre-crash replies would otherwise inflate the ratio)
    replies_sent = sum(
        r.metrics.get("replies_sent", 0) for r in com.replicas if r._running
    )
    # overload/degraded-mode evidence (ISSUE 1): how much inbound traffic
    # the priority shed dropped, how many sweeps the verify service
    # admission-rejected, and whether any replica is still flagged
    # degraded at window end. Client-side: retransmissions vs requests
    # that RECOVERED after a retry — the reconciliation for "unexplained
    # client timeouts" (VERDICT r5 weak #3): a shed-then-recovered
    # request now shows up here instead of vanishing into the timeout
    # column.
    shed_info = {
        "messages_shed": sum(
            r.metrics.get("messages_shed", 0) for r in com.replicas
        ),
        "sweeps_shed_overload": sum(
            r.metrics.get("sweeps_shed_overload", 0) for r in com.replicas
        ),
        "degraded_replicas": sum(
            1 for r in com.replicas if r.metrics.get("degraded_mode", 0)
        ),
        "client_retransmissions": sum(
            c.metrics.get("retransmissions", 0) for c in com.clients
        ),
        "client_recovered_after_retry": sum(
            c.metrics.get("recovered_after_retry", 0) for c in com.clients
        ),
    }
    if storm:
        # certificate-size evidence: the qc_mode claim is smaller failover
        # certificates — report the biggest ones actually built
        crash_info["max_viewchange_bytes"] = max(
            (r.metrics.get("max_viewchange_bytes", 0) for r in com.replicas),
            default=0,
        )
        crash_info["max_newview_bytes"] = max(
            (r.metrics.get("max_newview_bytes", 0) for r in com.replicas),
            default=0,
        )
    # verify-batch occupancy (VERDICT r3 #3): sampled BEFORE com.stop()
    # — stop() clears _running on every replica, which would always
    # empty this snapshot. Device-side numbers come from the SHARED
    # verifier's own counters, measured inside the device lock by the
    # holder: summing caller-side wall clocks across n replicas counts
    # lock wait once per blocked caller (up to n x underreport).
    verify_stats = {}
    if verifier == "tpu":
        v = shared_verifier
        verify_stats = dict(
            verify_calls=v.device_calls,
            verify_fresh_items=v.device_items,
            verify_batch_mean=(
                round(v.device_items / v.device_calls, 1)
                if v.device_calls
                else 0.0
            ),
            verify_ms_mean=(
                round(1e3 * v.device_seconds / v.device_calls, 1)
                if v.device_calls
                else 0.0
            ),
            verify_per_s_device=(
                round(v.device_items / v.device_seconds, 1)
                if v.device_seconds
                else 0.0
            ),
            # coalescing-service occupancy: how hard the device passes
            # actually batched across replicas, and what the CPU
            # small-batch path absorbed
            svc_device_passes=service.device_passes,
            svc_device_items=service.device_pass_items,
            svc_cpu_passes=service.cpu_passes,
            svc_cpu_items=service.cpu_pass_items,
            svc_max_coalesced=service.max_coalesced,
            svc_submissions=service.coalesced_submissions,
            svc_rtt_ms_ema=round(service.rtt_ms, 1),
            # overload-resilience evidence (ISSUE 1): bounded-admission
            # pressure, watchdog activity, and CPU reroute volume — the
            # post-mortem for any degraded window in this run
            svc_degraded=service.degraded,
            svc_max_pending_seen=service.max_pending_seen,
            svc_overload_rejections=service.overload_rejections,
            svc_watchdog_failovers=service.watchdog_failovers,
            svc_quarantine_probes=service.quarantine_probes,
            svc_cpu_reroute_passes=service.cpu_reroute_passes,
            svc_cpu_reroute_items=service.cpu_reroute_items,
            svc_cpu_reroute_chunks=service.cpu_reroute_chunks,
            svc_late_device_completions=service.late_device_completions,
            # shape stability (ISSUE 3): after warmup this must report
            # post_warm_compiles == 0 — a nonzero value means the run
            # paid a mid-window XLA compile (the r5 qc256 suspect)
            svc_device_shapes=shared_verifier.shape_snapshot(),
        )

    telemetry_end = _committee_telemetry(
        com, service if verifier == "tpu" else None
    )
    loop_lag = lag_gauge.snapshot()
    for wd in watchdogs:
        await wd.stop()
    for rec_f in recorders:
        await rec_f.stop()
    for srv in status_servers:
        await srv.stop()

    await com.stop()
    for tr in tracers.values():
        tr.close()
    for aud in auditors.values():
        aud.close()
    if verifier == "tpu":
        service.close()

    lat_ms = sorted(x * 1e3 for _, x in latencies)

    def _pctv(vals, p: float) -> float:
        # one percentile formula for every latency surface in the record
        # (p50_ms, the spec/final split): nearest-rank on a sorted list
        return vals[min(len(vals) - 1, int(p * len(vals)))] if vals else 0.0

    def pct(p: float) -> float:
        return _pctv(lat_ms, p)

    from simple_pbft_tpu.telemetry import (
        BENCH_SCHEMA_VERSION,
        wire_delta,
        wire_per_commit,
    )

    rec = {
        # the ledger's own schema stamp (ISSUE 12 satellite): the bench
        # ledger is what tools/bench_gate.py compares, and it had no
        # version while the telemetry snapshots have carried one since
        # PR 5 — the gate refuses cross-schema comparisons
        "schema_version": BENCH_SCHEMA_VERSION,
        "config": name,
        "n": n,
        "qc_mode": qc_mode,
        "speculative": speculative,
        "chaos": chaos or None,
        "verifier": verifier,
        "clients": n_clients,
        "outstanding": per_client * n_clients,
        "batch": batch,
        "seconds": round(elapsed, 1),
        "window_s": round(window, 1),
        "committed_req_s": round(committed / window, 1),
        # full-run rate: every completed request over the whole wall
        # clock including the drain tail (VERDICT r4 weak #2 — a run
        # that completes all traffic at t=41 s after a 30 s window is a
        # slow-warmup run, not a dead one; the windowed number alone
        # cannot tell them apart)
        "full_run_req_s": round(len(latencies) / max(elapsed, 1e-9), 1),
        "drain_tail_s": round(max(0.0, elapsed - seconds), 1),
        "completed_total": len(latencies),
        "p50_ms": round(pct(0.50), 2),
        "p99_ms": round(pct(0.99), 2),
        "client_timeouts": len(errors),
        "replica_exec_min": exec_counts[0] if exec_counts else 0,
        "replica_exec_max": exec_counts[-1] if exec_counts else 0,
        "replies_sent": replies_sent,
        "reply_fanout": round(
            replies_sent / max(1, exec_counts[-1] if exec_counts else 1), 1
        ),
        "repliers_cfg": com.cfg.repliers,
        "vs_reference_req_s": round(committed / window / 0.4, 1),  # ref ~0.4/s
    }
    rec.update(shed_info)
    rec.update(verify_stats)
    rec.update(crash_info)
    # speculative execution (ISSUE 15): the p50/p99 split the roadmap
    # acceptance gates on — spec-accept latency (client submit -> 2f+1
    # matching speculative marks) vs final-commit confirmation latency
    # (submit -> f+1 final replies) — plus the replica-side slot
    # counters and the execute.spec/execute.final span histograms that
    # attribute the win per percentile (already in rec["spans"])
    spec_lat = sorted(
        lat * 1e3
        for c in com.clients
        for (lat, kind) in getattr(c, "accept_latencies", ())
        if kind == "spec"
    )
    confirm_lat = sorted(
        lat * 1e3
        for c in com.clients
        for lat in getattr(c, "confirm_latencies", ())
    )

    rec["spec"] = {
        "executed": sum(
            r.metrics.get("spec_executed", 0) for r in com.replicas
        ),
        "confirmed": sum(
            r.metrics.get("spec_confirmed", 0) for r in com.replicas
        ),
        "rolled_back": sum(
            r.metrics.get("spec_rolled_back", 0) for r in com.replicas
        ),
        "rollbacks": sum(
            r.metrics.get("spec_rollbacks", 0) for r in com.replicas
        ),
        "replies_sent": sum(
            r.metrics.get("spec_replies_sent", 0) for r in com.replicas
        ),
        "client_spec_accepted": sum(
            c.metrics.get("spec_accepted", 0) for c in com.clients
        ),
        "client_final_confirms": sum(
            c.metrics.get("final_confirms", 0) for c in com.clients
        ),
        "client_spec_final_mismatch": sum(
            c.metrics.get("spec_final_mismatch", 0) for c in com.clients
        ),
    }
    if spec_lat:
        rec["p50_spec_latency_ms"] = round(_pctv(spec_lat, 0.50), 2)
        rec["p99_spec_latency_ms"] = round(_pctv(spec_lat, 0.99), 2)
    if confirm_lat:
        rec["p50_final_latency_ms"] = round(_pctv(confirm_lat, 0.50), 2)
        rec["p99_final_latency_ms"] = round(_pctv(confirm_lat, 0.99), 2)
    # wire accounting (ISSUE 12 tentpole): the measurement window's
    # per-kind msgs+bytes and the derived per-commit costs — msgs/commit,
    # bytes/commit, per-phase broadcast amplification (the O(n²) storm,
    # previously visible only as the reply_fanout scalar, is now a
    # first-class per-phase number in every record)
    wire_kinds = wire_delta(
        telemetry_start.get("wire_per_kind", {}),
        telemetry_end.get("wire_per_kind", {}),
    )
    slots_delta = (
        telemetry_end.get("exec_seq_max", 0)
        - telemetry_start.get("exec_seq_max", 0)
    )
    rec["wire"] = {
        "per_kind": wire_kinds,
        "per_commit": wire_per_commit(
            wire_kinds, slots_delta, max(1, committed)
        ),
    }
    # QC-plane fast path (ISSUE 3): certificate-verify lane occupancy —
    # batch sizes, pairing latency, queue pressure. Present whenever any
    # QC was verified this process (qc_mode configs; None otherwise).
    from simple_pbft_tpu.consensus import qc as qc_lane_mod

    lane_snap = qc_lane_mod.lane_snapshot()
    if lane_snap is not None:
        rec["qc_lane"] = lane_snap
    # start/end unified snapshots: the cell carries the telemetry that
    # explains it (e.g. a low committed_req_s with end.verify.quarantined
    # true and messages_shed high IS the diagnosis, no log forensics)
    rec["telemetry"] = {"start": telemetry_start, "end": telemetry_end}
    # per-stage latency attribution (ISSUE 4): every cell now carries
    # the stage histograms that say WHERE its p99 went, plus the
    # event-loop lag gauge (a starved dispatcher core is visible) and
    # any stall autopsies the watchdogs wrote
    rec["spans"] = spans_mod.snapshot()["stages"]
    # device-plane observatory (ISSUE 14): the per-dispatch ledger's
    # aggregates — dispatch rate, occupancy, effective verifies/s, pad
    # waste, per-shape counts — as a first-class record block, the
    # surface tools/bench_gate.py device floors and
    # tools/verify_observatory.py gate on
    rec["device"] = devledger_mod.snapshot()
    rec["loop_lag"] = loop_lag
    if watchdogs:
        rec["autopsy_dumps"] = sum(wd.dumps for wd in watchdogs)
    if sample_mod > 0:
        rec["trace_events"] = sum(t.events_emitted for t in tracers.values())
        rec["trace_dropped"] = sum(t.trace_dropped for t in tracers.values())
    if auditors:
        # accountability summary: any safety violation during the run,
        # broken down by invariant, with the union of accused replicas —
        # zero across the board is the honest-run clean bill
        by_kind = {}
        accused = set()
        for aud in auditors.values():
            for k, v in aud.by_kind.items():
                by_kind[k] = by_kind.get(k, 0) + v
            accused.update(aud.accused_ever)
        rec["audit"] = {
            "violations": sum(a.violations for a in auditors.values()),
            "observations": sum(a.observations for a in auditors.values()),
            "by_kind": dict(sorted(by_kind.items())),
            "accused": sorted(accused),
        }
    if schedule is not None:
        rec["faults"] = schedule.summary()
        rec["faults_applied"] = injector.applied_count
        rec["faults_skipped"] = injector.skipped
        rec["fault_crashes"] = injector.crashes_applied
        # byzantine wrappers (equivocate / fork_checkpoint events): how
        # many frames were actually forged — a detection test asserting
        # "the auditor accused rX" must also prove rX really misbehaved
        rec["fault_byzantine_injections"] = injector.byzantine_injections
    return rec


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1")
    # insecure = accept-everything backend: measures the consensus-plane
    # ceiling with verification free — the asymptote a fully-overlapped
    # device offload approaches (and reference-parity mode: the
    # reference verifies nothing)
    ap.add_argument(
        "--verifier", default="cpu", choices=["cpu", "tpu", "insecure"]
    )
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--outstanding", type=int, default=128)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--storm", action="store_true")
    ap.add_argument(
        "--crashes", type=int, default=3,
        help="storm: number of primary crash-stops (successive crashes "
        "race each new view's first commit — the hardest variant)",
    )
    ap.add_argument(
        "--chaos", default=None,
        help="fault injection for the run, e.g. drop=0.02,delay=0.03,"
        "dup=0.01,seed=42 (reproduces the committed soak numbers)",
    )
    ap.add_argument(
        "--fault-schedule", default=None,
        help="deterministic seeded fault schedule (simple_pbft_tpu/"
        "faults.py), e.g. seed=42,crashes=3,drops=1,delays=1,stalls=1 — "
        "the reproducible chaos/storm cell; crash counts here give the "
        "crash-count-matched storm A/B (stalls need --verifier tpu). "
        "Byzantine injectors: equiv=N arms equivocating primaries, "
        "forkckpt=N checkpoint forkers — pair with --flight-dir so the "
        "audit plane's ledgers prove detection (docs/AUDIT.md)",
    )
    ap.add_argument(
        "--replay", default=None, metavar="RECORD",
        help="replay the EXACT fault schedule of a previous run from "
        "its bench record (a JSON file, or a .jsonl ledger — last line "
        "wins): the record's faults block carries the complete (seed, "
        "horizon, event list, kind-table crc) tuple, so the schedule "
        "reconstructs without the original CLI spec; --seconds is "
        "overridden by the recorded horizon",
    )
    ap.add_argument(
        "--verify-deadline", type=float, default=60.0,
        help="tpu verify service: device dispatch deadline in seconds "
        "before the watchdog fails the sweep over to the CPU verifier "
        "and quarantines the device path (0 disables)",
    )
    ap.add_argument(
        "--verify-max-pending", type=int, default=65536,
        help="tpu verify service: pending-item cap; submits past it are "
        "admission-rejected with Overloaded instead of queued",
    )
    ap.add_argument(
        "--status-port-base", type=int, default=0,
        help="live telemetry: serve each replica's /metrics.json at "
        "127.0.0.1:(base+i) during the run (0 disables) — scrape with "
        "tools/pbft_top.py --endpoints or curl",
    )
    ap.add_argument(
        "--flight-dir", default=None,
        help="write per-replica flight-recorder JSONL (and trace JSONL "
        "when --trace-sample is set) under this directory; a SIGKILLed "
        "run still leaves its snapshot timeline",
    )
    ap.add_argument(
        "--trace-sample", type=float, default=0,
        help="phase-level request tracing: N > 1 keeps ~1/N of requests "
        "(deterministic hash sampling); a fraction in (0, 1] keeps that "
        "share — '--trace-sample 1.0' is the explicit full-fidelity "
        "debug mode; 0 off. The record carries trace_dropped so "
        "sampling loss is measurable",
    )
    ap.add_argument(
        "--stall-deadline", type=float, default=30.0,
        help="wedge autopsy (needs --flight-dir): seconds without a "
        "commit (with work outstanding) before a replica dumps "
        "<flight-dir>/<id>.autopsy.json naming the stalled stage "
        "(0 disables)",
    )
    ap.add_argument(
        "--view-timeout", type=float, default=0.0,
        help="failover timer override; the storm default (3 s) assumes "
        "view-change validation is fast — on a single-core host a 64-node "
        "certificate takes seconds to check, so raise this accordingly",
    )
    ap.add_argument(
        "--no-spec", action="store_true",
        help="disable speculative execution (ISSUE 15) — the A/B arm "
        "for attributing the spec-latency win; the record then carries "
        "no p50_spec_latency_ms field",
    )
    ap.add_argument(
        "--device-profile", type=float, default=0.0,
        help="arm ONE bounded jax.profiler capture of this many seconds "
        "per cell (needs --flight-dir; artifacts under "
        "<flight-dir>/device_profile). The always-on device ledger "
        "(rec['device']) does not need this — kernel forensics only",
    )
    args = ap.parse_args()
    # watchdog scales with the requested ladder: measurement time plus
    # generous per-config setup/teardown slack (large committees take tens
    # of seconds to wind up on a small host); env var still overrides
    n_configs = max(1, len([k for k in args.configs.split(",") if k.strip()]))
    default_budget = n_configs * (args.seconds + 120.0) + 60.0
    _start_watchdog(
        float(os.environ.get("BENCH_CONSENSUS_TIMEOUT", str(default_budget)))
    )

    ladder = {
        "1": dict(name="pbft-n4", n=4),
        "2": dict(name="pbft-n16", n=16),
        "3": dict(name="pbft-n64", n=64),
        "4": dict(name="bls-qc-n256", n=256, qc_mode=True),
        "100": dict(name="pbft-n100", n=100),
        # qc_mode at mid sizes: the storm comparison points — a NEW-VIEW
        # carries 2f+1 O(1) QCs instead of 2f+1 full vote certificates
        "qc16": dict(name="bls-qc-n16", n=16, qc_mode=True),
        "qc64": dict(name="bls-qc-n64", n=64, qc_mode=True),
        # the 10k req/s extrapolation's shape (cpu_budget_r04.md): O(n)
        # vote traffic at the reference-class committee size
        "qc100": dict(name="bls-qc-n100", n=100, qc_mode=True),
    }
    chaos = None
    if args.chaos:
        try:
            raw = dict(kv.split("=", 1) for kv in args.chaos.split(","))
            if not raw or any(
                k not in ("drop", "delay", "dup", "seed") for k in raw
            ):
                raise ValueError(args.chaos)
            # resolve to effective numeric values (defaults included) so
            # the emitted record reproduces the exact fault plan
            chaos = {
                "drop": float(raw.get("drop", 0.0)),
                "delay": float(raw.get("delay", 0.0)),
                "dup": float(raw.get("dup", 0.0)),
                "seed": int(raw.get("seed", 42)),
            }
        except ValueError:
            sys.exit(f"bad --chaos spec {args.chaos!r}: "
                     f"use drop=0.02,delay=0.03,dup=0.01,seed=42")

    replay_schedule = None
    if args.replay:
        from simple_pbft_tpu.faults import FaultSchedule

        with open(args.replay) as f:
            text = f.read()
        try:
            # a single JSON document (bench record, sim repro artifact —
            # artifacts are pretty-printed, so they span many lines)
            doc = json.loads(text)
        except json.JSONDecodeError:
            # a .jsonl ledger: the last record wins
            lines = [ln for ln in text.splitlines() if ln.strip()]
            doc = json.loads(lines[-1])
        faults = doc.get("faults") or (
            (doc.get("scenario") or {}).get("schedule")
        )
        if not faults:
            sys.exit(f"{args.replay!r} carries no faults block "
                     "(nothing to replay)")
        replay_schedule = FaultSchedule.from_summary(faults)
        args.seconds = replay_schedule.horizon
        print(f"[replay] {args.replay}: seed={replay_schedule.seed} "
              f"horizon={replay_schedule.horizon}s "
              f"events={len(replay_schedule.events)}"
              + (f" (recorded n={doc['n']})" if doc.get("n") else ""))

    for key in args.configs.split(","):
        key = key.strip()
        if key not in ladder:
            sys.exit(
                f"unknown config {key!r}: valid are "
                f"{sorted(ladder)} (config 5, the view-change storm, "
                f"runs via --storm over one of these committee sizes)"
            )
        cfg = ladder[key]
        resilience = dict(
            fault_spec=replay_schedule or args.fault_schedule,
            verify_deadline=args.verify_deadline,
            verify_max_pending=args.verify_max_pending,
            status_port_base=args.status_port_base,
            flight_dir=args.flight_dir,
            trace_sample=args.trace_sample,
            stall_deadline=args.stall_deadline,
            device_profile=args.device_profile,
            speculative=not args.no_spec,
        )
        if args.storm:
            rec = await run_config(
                f"viewchange-storm-{cfg['name']}", cfg["n"], args.seconds,
                args.clients, args.outstanding, args.verifier, args.batch,
                storm=True, view_timeout=args.view_timeout,
                qc_mode=cfg.get("qc_mode", False), chaos=chaos,
                max_crashes=args.crashes, **resilience,
            )
        else:
            rec = await run_config(
                cfg["name"], cfg["n"], args.seconds, args.clients,
                args.outstanding, args.verifier, args.batch,
                view_timeout=args.view_timeout,
                qc_mode=cfg.get("qc_mode", False), chaos=chaos,
                **resilience,
            )
        _emit(rec)


if __name__ == "__main__":
    asyncio.run(main())
